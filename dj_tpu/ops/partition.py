"""hash_partition: reorder a table by key-hash partition id.

Contract matches cudf::hash_partition as the reference uses it
(/root/reference/src/distributed_join.cpp:213-233,
/root/reference/src/shuffle_on.cpp:59-60): returns the table reordered so
partition p occupies rows [offsets[p], offsets[p+1]) plus the offsets
vector, with partition id = murmur3(key_row, seed) % npartitions.

TPU-first design: partition ids are a fused VPU hash pass; the reorder is
ONE stable variadic sort keyed on the small-int partition ids that
carries every fixed-width column as an extra sort operand — on TPU a
multi-operand sort is several times cheaper than argsort followed by one
random-access gather per column (gathers are latency-bound; see
search.py). Invalid (padding) rows get partition id = npartitions so
they sort to the tail and never enter any partition. Static shapes
throughout; offsets come from a partition-id histogram + cumsum.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.table import Column, StringColumn, Table
from . import hashing


def partition_ids(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
) -> jax.Array:
    """int32 partition id per row; padding rows get id == npartitions."""
    h = hashing.hash_table(table, on_columns, seed, hash_function)
    pid = (h % jnp.uint32(npartitions)).astype(jnp.int32)
    n = table.capacity
    valid = jnp.arange(n, dtype=jnp.int32) < table.count()
    return jnp.where(valid, pid, jnp.int32(npartitions))


# Above this partition count the one-hot histogram's npartitions passes
# over the pid vector cost more than one scatter-add; measured crossover
# is far higher than any realistic group_size * odf (phase_bench.py).
_ONEHOT_HIST_MAX = 256


def partition_counts_from_ids(pid: jax.Array, npartitions: int) -> jax.Array:
    """Per-partition row counts from a partition-id vector.

    For small partition counts a one-hot compare + column reduction is
    dramatically cheaper than a scatter-add histogram on TPU (scatters
    pay a per-element latency cost; the one-hot is npartitions fused
    sequential passes — measured ~10x faster at bench scale,
    scripts/phase_bench.py; 3.65 ms/100M at offset shapes). Padding
    rows carry pid == npartitions and match no bucket. Besides the
    shuffle offsets, this is the bucketed merged sort's range-partition
    histogram (ops/join.py `_bucketed_sort`, where ids are the packed
    word's top bits and never reach npartitions).
    """
    if npartitions <= _ONEHOT_HIST_MAX:
        buckets = jnp.arange(npartitions, dtype=pid.dtype)
        return jnp.sum(
            pid[:, None] == buckets[None, :], axis=0, dtype=jnp.int32
        )
    return jnp.zeros((npartitions,), jnp.int32).at[pid].add(1, mode="drop")


def salted_partition_ids(
    pid: jax.Array,
    npartitions: int,
    group_size: int,
    heavy: Sequence[int],
    replicas: int,
) -> jax.Array:
    """Scatter heavy destinations' rows across cyclic salt shards —
    the PROBE-side half of the salted replication tier
    (parallel.plan_adapt; the build side replicates via rotated
    exchange windows instead).

    ``heavy`` is the static set of heavy GLOBAL partition ids (batch
    b's destination d at ``b * group_size + d``). A row whose pid is
    heavy moves to partition ``b*n + (d + salt) % n`` with salt =
    row_position % replicas — within the SAME odf batch, so batch
    windows and sizing are untouched; every other row (padding's
    ``pid == npartitions`` included) keeps its pid. The build side's
    heavy partitions are replicated to exactly the peers
    ``(d + c) % n, c < replicas`` (dist_join's rotated copy windows),
    so each probe row still meets each matching build row EXACTLY
    once. Requires replicas <= group_size (distinct salt peers)."""
    import numpy as np

    assert 2 <= replicas <= group_size
    is_heavy = np.zeros(npartitions + 1, bool)
    for p in heavy:
        assert 0 <= p < npartitions, f"heavy pid {p} out of range"
        is_heavy[p] = True
    heavy_v = jnp.asarray(is_heavy)
    j = pid % group_size  # in-batch destination slot (garbage for pad)
    salt = (
        jnp.arange(pid.shape[0], dtype=jnp.int32) % replicas
    )
    return jnp.where(
        heavy_v[jnp.minimum(pid, npartitions)],
        pid - j + (j + salt) % group_size,
        pid,
    )


def partition_by_ids(
    table: Table, pid: jax.Array, npartitions: int
) -> tuple[Table, jax.Array]:
    """Reorder rows by a precomputed partition-id vector (padding rows
    carry ``pid == npartitions``) — the sort body of
    :func:`hash_partition`, split out so callers that remap ids first
    (the salted tier's :func:`salted_partition_ids`) share one
    reorder implementation."""
    counts = partition_counts_from_ids(pid, npartitions)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)]
    )
    # One stable sort keyed on pid carrying all fixed-width columns;
    # string columns ride the permutation (their chars need a gather
    # regardless).
    fixed = [
        (i, c) for i, c in enumerate(table.columns) if isinstance(c, Column)
    ]
    strings = [
        (i, c)
        for i, c in enumerate(table.columns)
        if isinstance(c, StringColumn)
    ]
    operands = [pid] + [c.data for _, c in fixed]
    if strings:
        operands.append(jnp.arange(table.capacity, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(
        tuple(operands), num_keys=1, is_stable=True
    )
    out_cols: list = [None] * table.num_columns
    for k, (i, c) in enumerate(fixed):
        out_cols[i] = Column(sorted_ops[1 + k], c.dtype)
    if strings:
        perm = sorted_ops[-1]
        for i, c in strings:
            out_cols[i] = c.take(perm)
    out = Table(tuple(out_cols), table.count())
    return out, offsets


def hash_partition(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
) -> tuple[Table, jax.Array]:
    """Reorder rows by partition id.

    Returns (reordered_table, offsets[int32, npartitions+1]); the
    reordered table keeps the input's capacity and valid_count, with all
    valid rows of partition p contiguous at [offsets[p], offsets[p+1]).
    """
    if npartitions == 1:
        # Degenerate case: one partition = the valid prefix, no reorder
        # (rows are already valid-prefix compacted).
        offsets = jnp.stack([jnp.int32(0), table.count()])
        return table, offsets
    pid = partition_ids(table, on_columns, npartitions, seed, hash_function)
    return partition_by_ids(table, pid, npartitions)


def partition_counts(offsets: jax.Array) -> jax.Array:
    """Per-partition row counts from an offsets vector."""
    return jnp.diff(offsets)
