"""hash_partition: reorder a table by key-hash partition id.

Contract matches cudf::hash_partition as the reference uses it
(/root/reference/src/distributed_join.cpp:213-233,
/root/reference/src/shuffle_on.cpp:59-60): returns the table reordered so
partition p occupies rows [offsets[p], offsets[p+1]) plus the offsets
vector, with partition id = murmur3(key_row, seed) % npartitions.

TPU-first design: partition ids are a fused VPU hash pass; the reorder is
ONE stable variadic sort keyed on the small-int partition ids that
carries every fixed-width column as an extra sort operand — on TPU a
multi-operand sort is several times cheaper than argsort followed by one
random-access gather per column (gathers are latency-bound; see
search.py). Invalid (padding) rows get partition id = npartitions so
they sort to the tail and never enter any partition. Static shapes
throughout; offsets come from a partition-id histogram + cumsum.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.table import Column, StringColumn, Table
from . import hashing


def partition_ids(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
) -> jax.Array:
    """int32 partition id per row; padding rows get id == npartitions."""
    h = hashing.hash_table(table, on_columns, seed, hash_function)
    pid = (h % jnp.uint32(npartitions)).astype(jnp.int32)
    n = table.capacity
    valid = jnp.arange(n, dtype=jnp.int32) < table.count()
    return jnp.where(valid, pid, jnp.int32(npartitions))


def hash_partition(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
    sort_by_key: Optional[int] = None,
) -> tuple[Table, jax.Array]:
    """Reorder rows by partition id.

    Returns (reordered_table, offsets[int32, npartitions+1]); the
    reordered table keeps the input's capacity and valid_count, with all
    valid rows of partition p contiguous at [offsets[p], offsets[p+1]).

    ``sort_by_key``: additionally order rows ASCENDING BY that
    fixed-width column within each partition (a second sort key on the
    same variadic sort). Slices of such partitions satisfy
    inner_join's ``right_sorted`` contract on single-peer groups.
    """
    if npartitions == 1 and sort_by_key is None:
        # Degenerate case: one partition = the valid prefix, no reorder
        # (rows are already valid-prefix compacted).
        offsets = jnp.stack([jnp.int32(0), table.count()])
        return table, offsets
    pid = partition_ids(table, on_columns, npartitions, seed, hash_function)
    # Offsets from a histogram: padding rows (pid == npartitions) fall
    # in the dropped overflow bucket.
    counts = jnp.zeros((npartitions,), jnp.int32).at[pid].add(1, mode="drop")
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)]
    )
    # One stable sort keyed on pid carrying all fixed-width columns;
    # string columns ride the permutation (their chars need a gather
    # regardless).
    fixed = [
        (i, c) for i, c in enumerate(table.columns) if isinstance(c, Column)
    ]
    strings = [
        (i, c)
        for i, c in enumerate(table.columns)
        if isinstance(c, StringColumn)
    ]
    num_keys = 1
    if sort_by_key is not None:
        # Put the secondary key column first among the carried operands
        # and extend the sort key prefix over it.
        key_col = table.columns[sort_by_key]
        assert isinstance(key_col, Column), "sort_by_key needs a fixed column"
        fixed = [(sort_by_key, key_col)] + [
            (i, c) for i, c in fixed if i != sort_by_key
        ]
        num_keys = 2
    operands = [pid] + [c.data for _, c in fixed]
    if strings:
        operands.append(jnp.arange(table.capacity, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(
        tuple(operands), num_keys=num_keys, is_stable=True
    )
    out_cols: list = [None] * table.num_columns
    for k, (i, c) in enumerate(fixed):
        out_cols[i] = Column(sorted_ops[1 + k], c.dtype)
    if strings:
        perm = sorted_ops[-1]
        for i, c in strings:
            out_cols[i] = c.take(perm)
    out = Table(tuple(out_cols), table.count())
    return out, offsets


def partition_counts(offsets: jax.Array) -> jax.Array:
    """Per-partition row counts from an offsets vector."""
    return jnp.diff(offsets)
