"""hash_partition: reorder a table by key-hash partition id.

Contract matches cudf::hash_partition as the reference uses it
(/root/reference/src/distributed_join.cpp:213-233,
/root/reference/src/shuffle_on.cpp:59-60): returns the table reordered so
partition p occupies rows [offsets[p], offsets[p+1]) plus the offsets
vector, with partition id = murmur3(key_row, seed) % npartitions.

TPU-first design: partition ids are a fused VPU hash pass; the reorder is
a single stable argsort of the small-int partition ids followed by one
gather per column. Invalid (padding) rows get partition id = npartitions
so they sort to the tail and never enter any partition. Static shapes
throughout; offsets come from a searchsorted over the sorted ids.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.table import Table
from . import hashing


def argsort32(keys: jax.Array) -> jax.Array:
    """Stable argsort returning int32 indices.

    jnp.argsort under x64 materializes int64 indices — at 100M rows
    that's an extra 400MB of HBM and doubled sort payload; int32 is
    always sufficient for per-shard row counts.
    """
    iota = jnp.arange(keys.shape[0], dtype=jnp.int32)
    _, perm = jax.lax.sort((keys, iota), num_keys=1, is_stable=True)
    return perm


def partition_ids(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
) -> jax.Array:
    """int32 partition id per row; padding rows get id == npartitions."""
    h = hashing.hash_table(table, on_columns, seed, hash_function)
    pid = (h % jnp.uint32(npartitions)).astype(jnp.int32)
    n = table.capacity
    valid = jnp.arange(n, dtype=jnp.int32) < table.count()
    return jnp.where(valid, pid, jnp.int32(npartitions))


def hash_partition(
    table: Table,
    on_columns: Sequence[int],
    npartitions: int,
    seed: int = hashing.DEFAULT_HASH_SEED,
    hash_function: str = hashing.HASH_MURMUR3,
) -> tuple[Table, jax.Array]:
    """Reorder rows by partition id.

    Returns (reordered_table, offsets[int32, npartitions+1]); the
    reordered table keeps the input's capacity and valid_count, with all
    valid rows of partition p contiguous at [offsets[p], offsets[p+1]).
    """
    if npartitions == 1:
        # Degenerate case: one partition = the valid prefix, no reorder
        # (rows are already valid-prefix compacted).
        offsets = jnp.stack([jnp.int32(0), table.count()])
        return table, offsets
    pid = partition_ids(table, on_columns, npartitions, seed, hash_function)
    perm = argsort32(pid)
    sorted_pid = pid[perm]
    offsets = jnp.searchsorted(
        sorted_pid, jnp.arange(npartitions + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    out = table.take(perm, valid_count=table.count())
    return out, offsets


def partition_counts(offsets: jax.Array) -> jax.Array:
    """Per-partition row counts from an offsets vector."""
    return jnp.diff(offsets)
