#!/usr/bin/env python
"""Pretty-print a crash-forensics black-box bundle (obs.forensics).

``DJ_OBS_BLACKBOX=<dir>`` leaves one
``blackbox-r<rank>-p<pid>.jsonl`` per dead process: one JSON section
per line, most-diagnostic first, written line-buffered so a dump torn
mid-write (the disk died with the process) loses only its tail. This
is the post-mortem side: point it at a bundle file (or the bundle
directory — every bundle in it prints, newest first) and it
reconstructs the story a fleet operator needs at 3am:

- WHY the process died (reason, exception type/message, traceback
  tail) from the ``meta`` section;
- WHAT it was doing: every open query timeline rendered as an
  indented span tree — the span the process died inside is marked
  ``OPEN`` — plus the last closed timelines for context;
- the flight-recorder ring tail, the non-default knob values, the
  headline metrics, scheduler/pressure state, capacity-ledger
  entries, and the last fleet snapshot.

Torn or malformed lines are counted and skipped, never fatal — a
black box that cannot be read after a real crash is theater. Exits 0
when at least one bundle yielded a ``meta`` section, 2 when nothing
readable was found.

Usage: python scripts/blackbox_read.py <bundle.jsonl | dir>
       [--ring-tail N] [--json]

``--json`` re-emits the parsed sections as one merged JSON object per
bundle (machine consumers; the chaos harness asserts on this).
"""

import argparse
import glob
import json
import os
import sys


def load_bundle(path):
    """Parse one bundle: {section_name: body} plus a torn-line count.
    Duplicate sections keep the LAST occurrence (a re-dump appends
    nothing — it rewrites — but be liberal in what we accept)."""
    sections = {}
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                name = obj.pop("section")
            except (ValueError, KeyError):
                torn += 1
                continue
            sections[str(name)] = obj
    return sections, torn


def _fmt_ts(ts, base):
    try:
        return f"+{float(ts) - base:9.3f}s"
    except (TypeError, ValueError):
        return " " * 11


def print_span_tree(summary, out):
    """One query timeline as an indented tree: spans nest by
    begin/end order, phases and instants print at their recorded
    depth. The span the process died inside has a begin with no end —
    marked OPEN, the detail the ISSUE's hard-death arm asserts on."""
    events = summary.get("events") or []
    base = None
    for e in events:
        if isinstance(e.get("ts"), (int, float)):
            base = float(e["ts"])
            break
    if base is None:
        base = 0.0
    depth = 0
    open_stack = []
    for e in events:
        ts = _fmt_ts(e.get("ts"), base)
        etype = e.get("type")
        if etype == "span" and e.get("phase") == "begin":
            out.write(
                f"    {ts} {'  ' * depth}[ {e.get('span', '?')}\n"
            )
            open_stack.append(e.get("span", "?"))
            depth += 1
        elif etype == "span":
            depth = max(0, depth - 1)
            if open_stack:
                open_stack.pop()
            tail = ""
            if e.get("outcome") is not None:
                tail = f" outcome={e['outcome']}"
            if e.get("seconds") is not None:
                tail += f" {e['seconds']:.4f}s"
            out.write(
                f"    {ts} {'  ' * depth}] {e.get('span', '?')}{tail}\n"
            )
        elif etype == "phase":
            out.write(
                f"    {ts} {'  ' * depth}~ phase {e.get('phase')}"
                f" {e.get('seconds', '?')}s"
                f" roofline={e.get('roofline_frac', '?')}\n"
            )
        else:
            keys = {
                k: v for k, v in e.items()
                if k not in ("type", "ts", "query_id", "tenant")
            }
            out.write(
                f"    {ts} {'  ' * depth}. {etype} {keys}\n"
            )
    for name in reversed(open_stack):
        out.write(f"    {'':11s} {'  ' * max(0, depth - 1)}"
                  f"] {name}  ** OPEN — process died inside **\n")
        depth = max(0, depth - 1)


def print_bundle(path, sections, torn, out):
    out.write(f"== bundle {path}"
              f"{f'  ({torn} torn line(s) skipped)' if torn else ''}\n")
    meta = sections.get("meta")
    if meta:
        out.write(
            f"  rank {meta.get('rank')} pid {meta.get('pid')} "
            f"reason={meta.get('reason')} ts={meta.get('ts')}\n"
        )
        out.write(f"  argv: {' '.join(meta.get('argv') or [])}\n")
        exc = meta.get("exc")
        if exc:
            out.write(
                f"  exception: {exc.get('type')}: {exc.get('message')}\n"
            )
            tb = (exc.get("traceback") or "").strip().splitlines()
            for ln in tb[-12:]:
                out.write(f"    | {ln}\n")
    traces = sections.get("traces") or {}
    for tr in traces.get("open") or []:
        out.write(
            f"  OPEN query {tr.get('query_id')} "
            f"tenant={tr.get('tenant')} "
            f"orphans={tr.get('orphans')} "
            f"terminal={tr.get('terminal')}\n"
        )
        print_span_tree(tr, out)
    closed = traces.get("closed") or []
    if closed:
        ids = [t.get("query_id") for t in closed]
        out.write(f"  closed queries ({len(closed)}): {ids}\n")
    ring = (sections.get("ring") or {}).get("events") or []
    if ring:
        out.write(f"  ring: {len(ring)} events; tail:\n")
        for e in ring[-args.ring_tail:]:
            keys = {
                k: v for k, v in e.items() if k not in ("type", "ts")
            }
            out.write(f"    {e.get('type')} {keys}\n")
    knobs = (sections.get("knobs") or {}).get("knobs") or []
    non_default = [
        k for k in knobs if isinstance(k, dict) and k.get("set")
    ]
    if knobs:
        out.write(f"  knobs: {len(knobs)} registered, "
                  f"{len(non_default)} explicitly set:\n")
        for k in non_default:
            out.write(f"    {k.get('name')}={k.get('effective')!r}\n")
    metrics = sections.get("metrics") or {}
    counters = metrics.get("counters") or {}
    if counters:
        out.write(f"  metrics: {len(counters)} counters, "
                  f"{len(metrics.get('gauges') or {})} gauges\n")
    serve = (sections.get("serve") or {}).get("schedulers")
    if serve:
        for s in serve:
            out.write(f"  scheduler: {s}\n")
    ledger = (sections.get("ledger") or {}).get("entries")
    if ledger:
        out.write(f"  ledger entries: {len(ledger)}\n")
    fleet = (sections.get("fleet") or {}).get("fleet")
    if fleet:
        out.write(
            f"  last fleet snapshot: "
            f"{len(fleet.get('ranks') or [])} rank(s)\n"
        )
    for name, body in sections.items():
        if "error" in body and set(body) == {"error"}:
            out.write(f"  section {name}: FAILED at dump time "
                      f"({body['error']})\n")


def bundle_paths(target):
    if os.path.isdir(target):
        found = sorted(
            glob.glob(os.path.join(target, "blackbox-*.jsonl")),
            key=os.path.getmtime,
            reverse=True,
        )
        return found
    return [target] if os.path.exists(target) else []


def main():
    global args
    ap = argparse.ArgumentParser(
        description="pretty-print DJ_OBS_BLACKBOX bundles"
    )
    ap.add_argument("target", help="bundle file or bundle directory")
    ap.add_argument("--ring-tail", type=int, default=16,
                    help="ring events to print per bundle")
    ap.add_argument("--json", action="store_true",
                    help="emit parsed sections as JSON per bundle")
    args = ap.parse_args()
    paths = bundle_paths(args.target)
    if not paths:
        print(f"blackbox_read: no bundle at {args.target}",
              file=sys.stderr)
        return 2
    ok = False
    for path in paths:
        try:
            sections, torn = load_bundle(path)
        except OSError as e:
            print(f"blackbox_read: {path}: {e}", file=sys.stderr)
            continue
        if args.json:
            print(json.dumps(
                {"path": path, "torn": torn, "sections": sections}
            ))
        else:
            print_bundle(path, sections, torn, sys.stdout)
        ok = ok or "meta" in sections
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
