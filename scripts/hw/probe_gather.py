"""Probe: does Mosaic support per-lane dynamic gather from VMEM?

If `jnp.take` (and take_along_axis) of a VMEM-resident value by a
runtime index vector compiles and runs on the real TPU, the fused
expand+materialize kernel (expansion ranks + meta/rpos gathers in one
pass) is buildable. Times it at production-ish sizes too.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 131_072  # one tile's worth


def kernel(val_ref, idx_ref, out_ref):
    vals = val_ref[:]
    idx = idx_ref[:]
    out_ref[:] = jnp.take(vals, idx, axis=0)


@jax.jit
def run(vals, idx):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((N,), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(vals, idx)


def main():
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.integers(0, 1 << 30, N, dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, N, N, dtype=np.int32))
    t0 = time.perf_counter()
    out = run(vals, idx)
    np.asarray(out[:1])
    print(f"compile+run OK in {time.perf_counter()-t0:.2f}s")
    want = np.asarray(vals)[np.asarray(idx)]
    np.testing.assert_array_equal(np.asarray(out), want)
    print("CORRECT")
    # Slope timing: 16 iterations in one jit.
    @jax.jit
    def loop(vals, idx, k):
        def body(_, c):
            v, i = c
            g = jnp.take(v, i, axis=0)

            def kern(val_ref, idx_ref, out_ref):
                out_ref[:] = jnp.take(val_ref[:], idx_ref[:], axis=0)

            g = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((N,), jnp.int32),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.VMEM),
                    pl.BlockSpec(memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(v, i)
            return v, (i + g) % N

        return jax.lax.fori_loop(0, k, body, (vals, idx))[1]

    np.asarray(loop(vals, idx, 1)[:1])
    t0 = time.perf_counter()
    np.asarray(loop(vals, idx, 1)[:1])
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(loop(vals, idx, 17)[:1])
    t17 = time.perf_counter() - t0
    per = (t17 - t1) / 16
    print(f"VMEM gather {N} elems: {per*1e6:.0f} us/iter "
          f"({per/N*1e9:.2f} ns/elem)")


if __name__ == "__main__":
    main()
