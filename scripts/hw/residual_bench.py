"""Attribute the odf=1 headline's residual: every non-sort op at size.

The measured primitives (partition/merged sorts, expansion ranks —
ARCHITECTURE.md phase table) explain only ~half of the 10.86 s
headline. The other half must live in the scans, stacks, and gathers
of inner_join's odf=1 shapes (S = 200M merged, out_cap = 49.5M,
L = R = 100M). The odf=1 full-stage breakdown OOMs (stage splitting
materializes what the fused jit recycles), so this benches each op
STANDALONE at exactly the join's shapes.

Wedge containment (the round-4 session-1 gather case wedged a tunnel
claim for 2h20m): ONE case per process — the driver loop wraps each
invocation in `timeout`. Run case k:  python residual_bench.py <case>
List cases:                           python residual_bench.py --list
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

ROWS = int(os.environ.get("DJ_RB_ROWS", 100_000_000))
BUCKET = 1.1
JOF = float(os.environ.get("DJ_BENCH_JOF", 0.33))  # match bench.py default
L = R = ROWS
S = L + R
OUT = int(JOF * int(ROWS * BUCKET))  # batch_sizing: jof * n * max(sl, sr)
REPS = int(os.environ.get("DJ_RB_REPS", 3))


def _bench(name, f, *args):
    """Compile, warm up, best-of-REPS. One JSON line."""
    # Keep and CALL the AOT executable — jit dispatch does not reuse
    # lower().compile() results (see sort_bench.py).
    t0 = time.perf_counter()
    jf = jax.jit(f).lower(*args).compile()
    compile_s = time.perf_counter() - t0
    out = jf(*args)
    np.asarray(jax.tree.leaves(out)[0][:1])  # block (axon-safe)
    best = None
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = jf(*args)
        np.asarray(jax.tree.leaves(out)[0][:1])
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    print(
        json.dumps(
            {
                "case": name,
                "ms": round(best * 1e3, 2),
                "compile_s": round(compile_s, 1),
                "S": S,
                "out": OUT,
            }
        ),
        flush=True,
    )


def _sorted_tags():
    """stand-in merged-order arrays: stag (i32), boundary pattern."""
    k = jax.random.PRNGKey(0)
    stag = jax.random.randint(k, (S,), 0, S, dtype=jnp.int32)
    return stag


CASES = {}


def case(f):
    CASES[f.__name__] = f
    return f


@case
def scan_cumsum_i32_S():
    """q_before = cumsum(is_q) over S (i32)."""
    x = (jax.random.randint(jax.random.PRNGKey(0), (S,), 0, 2, jnp.int32))
    _bench("scan_cumsum_i32_S", lambda v: jnp.cumsum(v), x)


@case
def scan_cummax_i64_S():
    """packed (ref_before, pos) cummax over S (i64)."""
    x = jax.random.randint(jax.random.PRNGKey(0), (S,), -1, 1 << 40, jnp.int64)
    _bench("scan_cummax_i64_S", lambda v: jax.lax.cummax(v), x)


@case
def scan_cumsum_i64_S():
    """csum = cumsum(cnt) over S (i64)."""
    x = jax.random.randint(jax.random.PRNGKey(0), (S,), 0, 3, jnp.int64)
    _bench("scan_cumsum_i64_S", lambda v: jnp.cumsum(v), x)


@case
def elemwise_decode_S():
    """the elementwise chain around the scans: decode stag, is_q,
    ref_before, boundary, hi/cnt/where (everything but the 3 scans)."""
    sp = jax.random.bits(jax.random.PRNGKey(0), (S,), dtype=jnp.uint32
                         ).astype(jnp.uint64) << jnp.uint64(17)
    tag_bits = int(S).bit_length()
    mask = jnp.uint64((1 << tag_bits) - 1)

    def f(sp):
        boundary = jnp.concatenate(
            [jnp.ones((1,), bool), (sp >> tag_bits)[1:] != (sp >> tag_bits)[:-1]]
        )
        raw = (sp & mask).astype(jnp.int32)
        stag = jnp.where(raw < R, raw + jnp.int32(L),
                         jnp.where(raw < S, raw - jnp.int32(R), jnp.int32(S)))
        is_q = (stag < L).astype(jnp.int32)
        pos = jnp.arange(S, dtype=jnp.int32)
        ref_before = pos - is_q  # stand-in for pos - cumsum (scan benched apart)
        hi = jnp.minimum(ref_before, jnp.int32(R))
        cnt = jnp.where(stag < L, jnp.maximum(hi, 0), 0).astype(jnp.int64)
        return boundary, stag, cnt

    _bench("elemwise_decode_S", f, sp)


@case
def meta_stack_gather():
    """meta = bitcast(stack([stag, run_start])) @S; gather at out."""
    stag = _sorted_tags()
    run_start = jnp.arange(S, dtype=jnp.int32)
    src = jax.random.randint(jax.random.PRNGKey(1), (OUT,), 0, S, jnp.int32)

    def f(a, b, src):
        meta = jax.lax.bitcast_convert_type(jnp.stack([a, b], -1), jnp.uint64)
        m32 = jax.lax.bitcast_convert_type(
            meta.at[src].get(mode="fill", fill_value=0), jnp.int32
        )
        return m32[:, 0], m32[:, 1]

    _bench("meta_stack_gather", f, stag, run_start, src)


@case
def stag_gather_out():
    """rtag = stag.at[rpos] — one i32 gather of out rows from S."""
    stag = _sorted_tags()
    rpos = jax.random.randint(jax.random.PRNGKey(2), (OUT,), 0, S, jnp.int32)
    _bench(
        "stag_gather_out",
        lambda s, r: s.at[r].get(mode="fill", fill_value=0),
        stag, rpos,
    )


@case
def lpack_stack_gather():
    """l_pack = stack 2 cols @L u64; gather [out, 2]."""
    a = jax.random.bits(jax.random.PRNGKey(3), (L,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    li = jax.random.randint(jax.random.PRNGKey(4), (OUT,), 0, L, jnp.int32)

    def f(a, li):
        pack = jnp.stack([a, a + jnp.uint64(1)], -1)
        rows = pack.at[li].get(mode="fill", fill_value=0)
        # 1-D per-column outputs, as the join materializes them — a 2-D
        # u64 OUTPUT would get the canonical T(8,128) layout (minor dim
        # padded 2 -> 128: a 50 GB allocation, measured OOM).
        return rows[:, 0], rows[:, 1]

    _bench("lpack_stack_gather", f, a, li)


@case
def rpack_gather():
    """r_pack 1 col @R u64; gather [out, 1]."""
    a = jax.random.bits(jax.random.PRNGKey(5), (R,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    ri = jax.random.randint(jax.random.PRNGKey(6), (OUT,), 0, R, jnp.int32)

    def f(a, ri):
        rows = a[:, None].at[ri].get(mode="fill", fill_value=0)
        return rows[:, 0]  # 1-D output; see lpack_stack_gather

    _bench("rpack_gather", f, a, ri)


@case
def t_scan_out():
    """t = j - cummax(where(run_starts(src), j, -1)) at out size."""
    src = jnp.sort(
        jax.random.randint(jax.random.PRNGKey(7), (OUT,), 0, S, jnp.int32)
    )

    def f(src):
        j32 = jnp.arange(OUT, dtype=jnp.int32)
        b = jnp.concatenate([jnp.ones((1,), bool), src[1:] != src[:-1]])
        return j32 - jax.lax.cummax(jnp.where(b, j32, -1))

    _bench("t_scan_out", f, src)


@case
def out_finalize():
    """valid_out wheres + bitcasts on 3 output u64 cols at out size."""
    x = jax.random.bits(jax.random.PRNGKey(8), (OUT, 3), dtype=jnp.uint32
                        ).astype(jnp.uint64)

    def f(x):
        valid = jnp.arange(OUT, dtype=jnp.int64) < jnp.int64(OUT // 2)
        cols = [jnp.where(valid, x[:, k], 0) for k in range(3)]
        return [jax.lax.bitcast_convert_type(c, jnp.int64) for c in cols]

    _bench("out_finalize", f, x)


@case
def expand_ranks_S():
    """pallas expand_ranks at the odf=1 shapes (csum S -> out)."""
    from dj_tpu.ops.pallas_expand import expand_ranks

    cnt = jax.random.randint(jax.random.PRNGKey(9), (S,), 0, 2, jnp.int64)
    csum = jnp.cumsum(cnt)
    _bench("expand_ranks_S", lambda c: expand_ranks(c, OUT), csum)


@case
def rpack_gather_flat():
    """same gather from a FLAT (R,) u64 operand (no [:, None])."""
    a = jax.random.bits(jax.random.PRNGKey(5), (R,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    ri = jax.random.randint(jax.random.PRNGKey(6), (OUT,), 0, R, jnp.int32)
    _bench(
        "rpack_gather_flat",
        lambda a, ri: a.at[ri].get(mode="fill", fill_value=0),
        a, ri,
    )


@case
def lpack_two_flat_gathers():
    """2 cols as two independent flat gathers (vs stack + [out,2])."""
    a = jax.random.bits(jax.random.PRNGKey(3), (L,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    li = jax.random.randint(jax.random.PRNGKey(4), (OUT,), 0, L, jnp.int32)

    def f(a, li):
        b = a + jnp.uint64(1)
        return (
            a.at[li].get(mode="fill", fill_value=0),
            b.at[li].get(mode="fill", fill_value=0),
        )

    _bench("lpack_two_flat_gathers", f, a, li)


@case
def rpack_gather_i32pair():
    """1 u64 col as TWO i32 planes stacked [R,2] — the measured
    pathology is that [R,1] u64 (1504 ms) costs MORE than [L,2] u64
    (1250 ms): if per-row cost follows dtype width, i32 planes halve
    it; if per-column, they double it. Decides a 5-line join change."""
    a = jax.random.bits(jax.random.PRNGKey(5), (R,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    ri = jax.random.randint(jax.random.PRNGKey(6), (OUT,), 0, R, jnp.int32)

    def f(a, ri):
        lo = jax.lax.bitcast_convert_type(
            (a & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32), jnp.int32
        )
        hi = jax.lax.bitcast_convert_type(
            (a >> jnp.uint64(32)).astype(jnp.uint32), jnp.int32
        )
        rows = jnp.stack([lo, hi], -1).at[ri].get(
            mode="fill", fill_value=0
        )
        return rows[:, 0], rows[:, 1]

    _bench("rpack_gather_i32pair", f, a, ri)


@case
def lpack_gather_i32quad():
    """2 u64 cols as FOUR i32 planes stacked [L,4] (vs [L,2] u64)."""
    a = jax.random.bits(jax.random.PRNGKey(3), (L,), dtype=jnp.uint32
                        ).astype(jnp.uint64)
    li = jax.random.randint(jax.random.PRNGKey(4), (OUT,), 0, L, jnp.int32)

    def f(a, li):
        b = a + jnp.uint64(1)
        planes = []
        for col in (a, b):
            planes.append(jax.lax.bitcast_convert_type(
                (col & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
                jnp.int32,
            ))
            planes.append(jax.lax.bitcast_convert_type(
                (col >> jnp.uint64(32)).astype(jnp.uint32), jnp.int32
            ))
        rows = jnp.stack(planes, -1).at[li].get(mode="fill", fill_value=0)
        return tuple(rows[:, k] for k in range(4))

    _bench("lpack_gather_i32quad", f, a, li)


@case
def join_scans_S():
    """pallas_scan.join_scans at the odf=1 shapes (S merged)."""
    from dj_tpu.ops.pallas_scan import join_scans

    tag_bits = max(1, int(S).bit_length())
    key = jnp.sort(
        jax.random.randint(jax.random.PRNGKey(10), (S,), 0, 2 * ROWS,
                           jnp.int64)
    ).astype(jnp.uint64)
    sp = (key << tag_bits) | jax.random.randint(
        jax.random.PRNGKey(11), (S,), 0, S, jnp.int64
    ).astype(jnp.uint64)

    def f(sp):
        return join_scans(
            sp,
            jnp.int32(ROWS),
            jnp.int32(ROWS),
            tag_bits=tag_bits,
            L=L,
            R=R,
        )

    _bench("join_scans_S", f, sp)


@case
def expand_values_S():
    """pallas_expand.expand_values at the odf=1 shapes (S -> out).

    DJ_VMETA_PRECISION picks the dot precision under test."""
    from dj_tpu.ops.pallas_expand import expand_values

    cnt = jax.random.randint(jax.random.PRNGKey(9), (S,), 0, 2, jnp.int32)
    csum = jnp.cumsum(cnt)
    stag = _sorted_tags()
    run_start = jnp.arange(S, dtype=jnp.int32)
    _bench(
        "expand_values_S",
        lambda c, n, s, r: expand_values(c, n, s, r, OUT),
        csum, cnt, stag, run_start,
    )


@case
def expand_vfull_S():
    """pallas_expand.expand_vfull at the odf=1 shapes: the complete
    vcarry output phase (src walk + rpos eq-walk) in one kernel.
    DJ_VMETA_PRECISION picks the dot precision under test."""
    from dj_tpu.ops.pallas_expand import expand_vfull

    cnt = jax.random.randint(jax.random.PRNGKey(9), (S,), 0, 2, jnp.int32)
    csum = jnp.cumsum(cnt)
    run_start = jnp.arange(S, dtype=jnp.int32)
    planes = [
        jax.random.randint(jax.random.PRNGKey(20 + i), (S,), -(2**31),
                           2**31 - 1, jnp.int32)
        for i in range(4)  # 2 payload planes + 2 key planes
    ]
    max_run = jnp.int32(1)  # unique-key regime, margin walk minimal

    def f(c, n, r, p0, p1, kl, kh):
        return expand_vfull(c, n, r, (p0, p1), kl, kh, max_run, OUT)

    _bench("expand_vfull_S", f, csum, cnt, run_start, *planes)


def main():
    names = sys.argv[1:]
    if names == ["--list"]:
        print("\n".join(CASES))
        return
    if not names:
        names = list(CASES)
    for n in names:
        CASES[n]()


if __name__ == "__main__":
    main()
