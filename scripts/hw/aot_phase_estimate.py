"""Predicted phase economics from XLA's own cost model, no chip needed.

AOT-compiles the EXACT 1-chip benchmark computation (the production
distributed_inner_join on a 1-device topology at DJ_BENCH_ROWS scale)
for a v5e target and aggregates the scheduled HLO's per-op
``estimated_cycles`` backend_config by phase (sort / scan-fusions /
gather / scatter / other). These are COMPILER ESTIMATES — the
measured table (the round-4 hardware suites) supersedes them — but they are
the first hardware-grounded attribution of where the 100M join's time
goes, and they were produced during the round-4 tunnel outage when no
measurement was possible.

Run: scripts/hw/run_aot_phase_estimate.sh  (strips axon env).
Output: one JSON line; full HLO at /tmp/aot_bench_hlo.txt.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Pin the TPU-runtime kernel plan: inner_join's unset-env defaults are
# PLATFORM-dependent (pallas on TPU, hist elsewhere) and this script
# traces on a CPU host — without the pins it would analyze the hist
# module while the chip runs pallas, a silent wrong-module attribution.
os.environ.setdefault("DJ_JOIN_EXPAND", "pallas-vmeta")
os.environ.setdefault("DJ_JOIN_SCANS", "pallas")

import jax.numpy as jnp
from jax.experimental import topologies

import dj_tpu
from dj_tpu.core.table import Column, Table
from dj_tpu.parallel.dist_join import _build_join_fn, _env_key

ROWS = int(os.environ.get("DJ_BENCH_ROWS", 100_000_000))
ODF = int(os.environ.get("DJ_BENCH_ODF", 1))
BUCKET = float(os.environ.get("DJ_BENCH_BUCKET", 1.1))
JOF = float(os.environ.get("DJ_BENCH_JOF", 0.33))

_CYC = re.compile(r'"estimated_cycles":"(\d+)"')
V5E_HZ = 940e6  # v5e core clock, for a rough cycles->ms conversion


def classify(line: str) -> str:
    if " sort(" in line or "sort." in line.split("=")[0]:
        return "sort"
    if "scatter" in line:
        return "scatter"
    if "gather" in line:
        return "gather"
    if "cummax" in line or "cumsum" in line or "reduce-window" in line:
        return "scan"
    if "fusion" in line:
        return "fusion(elementwise/other)"
    if "custom-call" in line:
        return "custom-call(pallas)"
    if "copy" in line:
        return "copy"
    return "other"


def main():
    topo_desc = topologies.get_topology_desc("v5e:2x2", "tpu")
    topology = dj_tpu.make_topology(devices=list(topo_desc.devices)[:1])
    config = dj_tpu.JoinConfig(
        over_decom_factor=ODF, bucket_factor=BUCKET, join_out_factor=JOF
    )
    fn = _build_join_fn(
        topology, config, (0,), (0,), ROWS, ROWS, _env_key()
    )
    sh = topology.row_sharding()
    i64 = jax.ShapeDtypeStruct((ROWS,), jnp.int64, sharding=sh)
    cnt = jax.ShapeDtypeStruct((1,), jnp.int32, sharding=sh)
    tbl = Table((Column(i64, dj_tpu.dtypes.int64),
                 Column(i64, dj_tpu.dtypes.int64)))
    compiled = fn.lower(tbl, cnt, tbl, cnt).compile()
    hlo = compiled.as_text()
    with open("/tmp/aot_bench_hlo.txt", "w") as f:
        f.write(hlo)

    phases: dict[str, float] = {}
    top: list[tuple[int, str]] = []
    for ln in hlo.splitlines():
        m = _CYC.search(ln)
        if not m:
            continue
        cyc = int(m.group(1))
        phases[classify(ln)] = phases.get(classify(ln), 0) + cyc
        name = ln.strip().split(" =")[0][:60]
        top.append((cyc, name))
    top.sort(reverse=True)
    total = sum(phases.values())
    out = {
        "rows": ROWS,
        "odf": ODF,
        "total_estimated_cycles": total,
        "total_estimated_ms": round(total / V5E_HZ * 1e3, 1),
        "phase_cycles_pct": {
            k: round(100 * v / total, 1)
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1])
        },
        "phase_estimated_ms": {
            k: round(v / V5E_HZ * 1e3, 1)
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1])
        },
        "top_ops": [
            {"est_ms": round(c / V5E_HZ * 1e3, 1), "op": n}
            for c, n in top[:12]
        ],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
