"""Do the Pallas expansion kernels LOWER under real Mosaic? (no device)

Round-3 verdict #4: the three merge-path kernels had only ever executed
in interpret mode on CPU; whether Mosaic accepts the tile geometry, the
dynamic-slice DMAs, and the margin trick was unknown. The local libtpu
can AOT-compile for a v5e topology with no chip attached, which answers
the LOWERING half immediately (perf still needs the chip).

Compiles each kernel mode at production geometry AND at the bench's
out_cap-sized shapes, plus the full inner_join with DJ_JOIN_EXPAND set,
for a single v5e device. Prints one PASS/FAIL line per case.

Run: env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu TPU_WORKER_HOSTNAMES=localhost \
      python scripts/hw/probe_mosaic_lower.py
"""

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import Mesh

from dj_tpu.utils import compat

# Smallest valid v5e topology is one host's 2x2; kernels compile
# replicated (P()) so each device runs the identical single-chip
# program — the lowering answer is the same as a true 1-chip compile.
TOPO = topologies.get_topology_desc("v5e:2x2", "tpu")
MESH = Mesh(TOPO.devices, ("d",))
REP = NamedSharding(MESH, P())


def try_compile(name, fn, *args):
    # Mosaic kernels cannot be auto-partitioned: wrap replicated over
    # the probe mesh, as the production pipeline wraps in shard_map.
    wrapped = compat.shard_map(
        fn,
        mesh=MESH,
        in_specs=tuple(P() for _ in args),
        out_specs=jax.tree.map(lambda _: P(), jax.eval_shape(fn, *args)),
        check_vma=False,
    )
    try:
        jax.jit(wrapped).lower(*args).compile()
        print(f"PASS {name}", flush=True)
        return True
    except Exception as e:
        msg = str(e).replace("\n", " ")[:300]
        print(f"FAIL {name}: {type(e).__name__}: {msg}", flush=True)
        if os.environ.get("DJ_PROBE_TRACE"):
            traceback.print_exc()
        return False


def sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=REP)


def main():
    from dj_tpu.ops import pallas_expand as pe

    S = 2 * 1024 * 1024  # merged size stand-in
    n_out = 1024 * 1024
    csum = sds((S,), jnp.int64)
    i32 = sds((S,), jnp.int32)
    scalar = sds((), jnp.int32)

    try_compile(
        "expand_ranks", lambda c: pe.expand_ranks(c, n_out), csum
    )
    try_compile(
        "expand_gather",
        lambda c, lo, hi: pe.expand_gather(c, lo, hi, n_out),
        csum, i32, i32,
    )
    try_compile(
        "expand_join",
        lambda c, st, rs, mr: pe.expand_join(c, st, rs, mr, n_out),
        csum, i32, i32, scalar,
    )

    # Full inner_join with each kernel mode (what the bench A/B runs),
    # small-but-production-shaped.
    import dj_tpu
    from dj_tpu.core.table import Column, Table

    rows = 4 * 1024 * 1024
    i64 = sds((rows,), jnp.int64)
    tbl = Table((Column(i64, dj_tpu.dtypes.int64),
                 Column(i64, dj_tpu.dtypes.int64)))
    for mode in ("hist", "pallas", "pallas-fused", "pallas-join"):
        os.environ["DJ_JOIN_EXPAND"] = mode
        try_compile(
            f"inner_join[{mode}]",
            lambda l, r: dj_tpu.inner_join(
                l, r, [0], [0], out_capacity=rows
            ),
            tbl, tbl,
        )


if __name__ == "__main__":
    main()
