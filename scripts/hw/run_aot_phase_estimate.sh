#!/usr/bin/env bash
# Compile-only predicted phase economics for the 1-chip bench pipeline
# (local libtpu; safe during a tunnel outage).
set -u
cd /root/repo
env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
    JAX_PLATFORMS=cpu TPU_WORKER_HOSTNAMES=localhost \
    python -u scripts/hw/aot_phase_estimate.py "$@"
