"""Merge-tier crossover study: XLA concat+lax.sort vs the merge-path
bitonic Pallas pass (ops/pallas_merge.py, DJ_JOIN_MERGE=pallas) vs the
zero-sort PROBE tier (core.search.run_bounds, DJ_JOIN_MERGE=probe) on
prepared-join-shaped operands.

The prepared fast path (dist_join.prepare_join_side) leaves the merge
as the per-query sort cost: the XLA tier re-sorts the concatenation
(log2(S) merge passes over S words), the pallas tier does ONE
HBM read+write plus log2(2T) VPU compare-exchange stages per tile,
and the probe tier does NO merge at all — 2 x log2(R) gathers of the
(unsorted) query batch against the resident run yield the match
bounds directly. The round-5 Batcher sort lost the compute-vs-
bandwidth trade at FULL sort depth (VPU-compute-bound, 26% slower);
at merge depth 1 — and at gather-vs-merge for probe — the balance is
unknown on this chip: THIS script is the A/B that decides promotion
(flip ops/join.py TPU_DEFAULT_MERGE via scripts/hw/promote.py only if
speedup > 1.02 at the headline size AND exact — the same gate
protocol as sort_bucket_crossover.py; promote.py adjudicates
xla vs pallas vs probe in one transaction).

Operands mirror a prepared batch: a = the resident build run
(range-compressed keys << tag_bits | rank, sentinel tail), b = a
freshly sorted probe batch of equal scale (the probe arm searches the
PRE-sort query words — its tier never sorts them). Pallas
bit-exactness is checked against lax.sort(concat) on a strided sample
+ the extremes (a full host pull through the tunnel costs minutes);
probe exactness is the on-device lower/upper-bound predicate
(a[lo-1] < q <= a[lo], a[hi-1] <= q < a[hi]) reduced to one bool.

Emits one JSON line per case:
  {"metric": "merge_crossover", "impl": "pallas", "n", "tile",
   "pad_frac", "xla_ms", "pallas_ms", "speedup", "exact"}
  {"metric": "merge_crossover", "impl": "probe", "n", "pad_frac",
   "xla_ms", "probe_ms", "speedup", "exact"}
(The probe arm's xla_ms baseline is the same concat-sort; its timing
excludes both tiers' downstream scans/expansion — a bias FAVORING
xla/pallas, which still owe S-sized scans the probe tier skips.)
A lowering/compile failure records an "error" case — compiled-Mosaic
viability of the kernel's unaligned DMA starts is part of what this
study answers.

The probe arm additionally sweeps QUERY FRACTIONS
(DJ_MERGE_XOVER_QFRACS): its economics are 2 x log2(R) gathers of the
QUERY count vs a sort of run+queries, so it wins when query batches
are small relative to the resident run (the steady-state serving
shape) and can lose at symmetric sizes — both regimes are measured,
each against its own sort-of-the-same-operands xla baseline.

Run on the chip: python scripts/hw/merge_crossover.py
Env: DJ_MERGE_XOVER_SIZES=65000000,200000000   (S = |a| + |b|)
     DJ_MERGE_XOVER_TILES=16384,32768,65536
     DJ_MERGE_XOVER_PAD=0,0.33
     DJ_MERGE_XOVER_QFRACS=0.5,0.0625          (queries = S * frac)
     DJ_MERGE_XOVER_REPEAT=3
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

SIZES = [
    int(s)
    for s in os.environ.get(
        "DJ_MERGE_XOVER_SIZES", "65000000,200000000"
    ).split(",")
]
TILES = [
    int(t)
    for t in os.environ.get(
        "DJ_MERGE_XOVER_TILES", "16384,32768,65536"
    ).split(",")
]
PAD_FRACS = [
    float(f) for f in os.environ.get("DJ_MERGE_XOVER_PAD", "0,0.33").split(",")
]
# Probe-arm query counts as fractions of S: 0.5 = the symmetric merge
# shape (comparable to the pallas cases), 1/16 = the small-query
# serving shape the probe tier targets.
Q_FRACS = [
    float(f)
    for f in os.environ.get(
        "DJ_MERGE_XOVER_QFRACS", "0.5,0.0625"
    ).split(",")
]
REPEAT = int(os.environ.get("DJ_MERGE_XOVER_REPEAT", "3"))
# Off-chip smoke only: run the kernel interpreted (timings meaningless,
# exactness + plumbing real).
INTERPRET = os.environ.get("DJ_MERGE_XOVER_INTERPRET", "0") == "1"


def _time(fc, *args) -> float:
    ts = []
    for _ in range(REPEAT):
        t0 = time.perf_counter()
        out = fc(*args)
        np.asarray(out[:1])  # axon tunnel: materialize to sync
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _operand(key, n, half, tag_bits, tag_offset, pad_frac):
    """One prepared-shaped operand: range-compressed key << tag_bits |
    tag, sentinel-padded tail. Returns (sorted, raw): the ascending run
    the merge tiers consume, plus the PRE-sort words — the probe arm's
    query vector (its tier searches unsorted batches)."""
    k = jax.random.randint(key, (half,), 0, n, dtype=jnp.int64).astype(
        jnp.uint64
    )
    x = (k << jnp.uint64(tag_bits)) | (
        jnp.arange(half, dtype=jnp.uint64) + jnp.uint64(tag_offset)
    )
    if pad_frac:
        nvalid = int(half * (1 - pad_frac))
        x = jnp.where(jnp.arange(half) < nvalid, x, ~jnp.uint64(0))
    return jax.lax.sort(x), x


def _bounds_exact(run, q, lo, hi):
    """On-device lower/upper-bound correctness predicate (one bool to
    the host — no full pull through the tunnel): lo is the first index
    with run[i] >= q, hi the first with run[i] > q, for EVERY query."""
    R = run.shape[0]
    lom1 = run.at[jnp.clip(lo - 1, 0, R - 1)].get()
    loat = run.at[jnp.clip(lo, 0, R - 1)].get()
    him1 = run.at[jnp.clip(hi - 1, 0, R - 1)].get()
    hiat = run.at[jnp.clip(hi, 0, R - 1)].get()
    ok = jnp.all(((lo == 0) | (lom1 < q)) & ((lo == R) | (loat >= q)))
    ok &= jnp.all(((hi == 0) | (him1 <= q)) & ((hi == R) | (hiat > q)))
    return ok & jnp.all((0 <= lo) & (lo <= hi) & (hi <= R))


def main():
    from dj_tpu.core.search import run_bounds
    from dj_tpu.ops.pallas_merge import merge_sorted_u64

    for S in SIZES:
      for pad_frac in PAD_FRACS:
        half = S // 2
        tag_bits = max(1, int(S).bit_length())
        ka, kb = jax.random.split(jax.random.PRNGKey(0))
        a, _ = _operand(ka, S, half, tag_bits, 0, pad_frac)
        b, b_raw = _operand(kb, S, half, tag_bits, half, pad_frac)
        np.asarray(a[:1]), np.asarray(b[:1])

        xla = jax.jit(
            lambda x, y: jax.lax.sort(jnp.concatenate([x, y]))
        ).lower(a, b).compile()
        xla_out = xla(a, b)
        xla_ms = _time(xla, a, b) * 1e3

        for tile in TILES:
            try:
                f = jax.jit(
                    lambda x, y, t=tile: merge_sorted_u64(
                        x, y, tile=t, interpret=INTERPRET
                    )
                ).lower(a, b).compile()
                out = f(a, b)
                step = max(1, S // 1_000_000)
                exact = bool(
                    np.array_equal(
                        np.asarray(out[::step]), np.asarray(xla_out[::step])
                    )
                    and np.asarray(out[-1]) == np.asarray(xla_out[-1])
                )
                ms = _time(f, a, b) * 1e3
                print(json.dumps({
                    "metric": "merge_crossover", "impl": "pallas",
                    "n": S, "tile": tile, "pad_frac": pad_frac,
                    "xla_ms": round(xla_ms, 1),
                    "pallas_ms": round(ms, 1),
                    "speedup": round(xla_ms / ms, 3),
                    "exact": exact,
                }), flush=True)
            except Exception as e:  # noqa: BLE001 - sweep must finish
                print(json.dumps({
                    "metric": "merge_crossover", "impl": "pallas",
                    "n": S, "tile": tile, "pad_frac": pad_frac,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }), flush=True)

        # Probe arm (no tile parameter): bounds of the UNSORTED query
        # words in the resident run — the per-query work the probe
        # tier does INSTEAD of any merge or left sort — at each query
        # fraction, against the sort-of-the-same-operands xla
        # baseline. The baselines exclude the S-sized scans xla still
        # owes downstream (a bias favoring xla; see module docstring).
        for q_frac in Q_FRACS:
            nq = max(1, min(int(S * q_frac), half))
            q = b_raw[:nq]
            try:
                qx = jax.jit(
                    lambda x, y: jax.lax.sort(jnp.concatenate([x, y]))
                ).lower(a, q).compile()
                qx(a, q)
                qxla_ms = _time(qx, a, q) * 1e3
                fb = jax.jit(run_bounds).lower(a, q).compile()
                lo, hi = fb(a, q)
                exact = bool(np.asarray(
                    jax.jit(_bounds_exact)(a, q, lo, hi)
                ))
                pms = _time(fb, a, q) * 1e3
                print(json.dumps({
                    "metric": "merge_crossover", "impl": "probe",
                    "n": S, "q_frac": q_frac, "pad_frac": pad_frac,
                    "xla_ms": round(qxla_ms, 1),
                    "probe_ms": round(pms, 1),
                    "speedup": round(qxla_ms / pms, 3),
                    "exact": exact,
                }), flush=True)
            except Exception as e:  # noqa: BLE001 - sweep must finish
                print(json.dumps({
                    "metric": "merge_crossover", "impl": "probe",
                    "n": S, "q_frac": q_frac, "pad_frac": pad_frac,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }), flush=True)


if __name__ == "__main__":
    main()
