#!/usr/bin/env bash
# Round-6 suite: prepared-build-side qualification + merge-tier A/B.
#   1. Prepared serving bench: prep-inclusive first query + amortized
#      per-query wall at the 100M headline (bench --prepared --repeat),
#      on ALL THREE merge tiers (xla / pallas / probe) — the xla-tier
#      entry doubles as the merge promotion's incumbent.
#   2. merge_crossover.py: concat+lax.sort vs the merge-path bitonic
#      pass vs the zero-sort probe bounds on prepared-shaped operands
#      (speedup-AND-exact gate per arm, same protocol as
#      sort_bucket_crossover.py; a Mosaic lowering failure is an honest
#      error case that simply fails that arm's gate).
#   3. promote.py: adjudicates TPU_DEFAULT_MERGE xla vs pallas vs probe
#      with numbers in one transaction — flips only if an arm's gate
#      AND its prepared-bench comparison both pass, smoke-tested and
#      committed with pathspec isolation.
# NO kill-timeouts (tunnel-wedge lesson, ROUND4_NOTES); every python
# entry self-watchdogs.
set -u
. "$(dirname "$0")/lib.sh"

blog_each() {
    local name=$1
    grep '^{' "/tmp/hw/$name.out" 2>/dev/null | grep -v '"error"' \
        | while IFS= read -r line; do
        echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
             "\"tag\": \"$name\", \"bench\": $line}" >> BENCH_LOG.jsonl
    done
}

# Prepared serving benches: 4 queries against one prepared build side.
# The unprepared baseline for the amortization claim is the round's
# plain bench entry (bench_default from r04d/r05, or re-run here).
run 0 bench_default python -u bench.py
blog bench_default 100000000
run 0 bench_prepared_xla env DJ_BENCH_PREPARED=1 DJ_BENCH_REPEAT=4 \
    python -u bench.py
blog bench_prepared_xla 100000000
run 0 bench_prepared_pallas env DJ_BENCH_PREPARED=1 DJ_BENCH_REPEAT=4 \
    DJ_JOIN_MERGE=pallas python -u bench.py
blog bench_prepared_pallas 100000000
run 0 bench_prepared_probe env DJ_BENCH_PREPARED=1 DJ_BENCH_REPEAT=4 \
    DJ_JOIN_MERGE=probe python -u bench.py
blog bench_prepared_probe 100000000

# Merge-tier crossover on prepared-shaped operands.
run 0 merge_xover python -u scripts/hw/merge_crossover.py
blog_each merge_xover

# Default promotion (expand knob re-adjudicated too — promote.py is
# idempotent against already-promoted constants), then re-confirm the
# scored default end to end.
run 0 promote python -u scripts/hw/promote.py
if grep -q "PROMOTED" /tmp/hw/promote.out; then
    run 0 bench_promoted python -u bench.py
    blog bench_promoted 100000000
    run 0 bench_promoted_prepared env DJ_BENCH_PREPARED=1 \
        DJ_BENCH_REPEAT=4 python -u bench.py
    blog bench_promoted_prepared 100000000
    git add BENCH_LOG.jsonl measurements 2>/dev/null
    git commit -q -m "Record promoted-default bench confirmation" || true
fi
log "R06 SUITE DONE"
