"""Does the Pallas merge sort LOWER under real Mosaic? (no device)

Same method as probe_mosaic_lower.py: the local libtpu AOT-compiles
for a v5e topology with no chip attached, which answers the lowering
half of round-4's sort-kernel question immediately (perf needs the
chip: scripts/hw/probe_sort.py / suite.sh).

Cases: the pass-1 tile-sort kernel, one merge pass, the full sort_u64
at production geometry and benchmark-like sizes, and the full
inner_join with DJ_JOIN_SORT=pallas.

Run: env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu TPU_WORKER_HOSTNAMES=localhost \
      python scripts/hw/probe_sort_lower.py
"""

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.sharding import Mesh

TOPO = topologies.get_topology_desc("v5e:2x2", "tpu")
MESH = Mesh(TOPO.devices, ("d",))
REP = NamedSharding(MESH, P())


def try_compile(name, fn, *args):
    wrapped = jax.shard_map(
        fn,
        mesh=MESH,
        in_specs=tuple(P() for _ in args),
        out_specs=jax.tree.map(lambda _: P(), jax.eval_shape(fn, *args)),
        check_vma=False,
    )
    try:
        jax.jit(wrapped).lower(*args).compile()
        print(f"PASS {name}", flush=True)
        return True
    except Exception as e:
        msg = str(e).replace("\n", " ")[:300]
        print(f"FAIL {name}: {type(e).__name__}: {msg}", flush=True)
        if os.environ.get("DJ_PROBE_TRACE"):
            traceback.print_exc()
        return False


def sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=REP)


def main():
    from dj_tpu.ops import pallas_sort as ps

    n_tiles = 4 * ps.T_OUT
    u32 = sds((n_tiles,), jnp.uint32)
    try_compile(
        "tile_sort",
        lambda h, lo: ps._tile_sort(h, lo, ps.T_OUT, False),
        u32, u32,
    )
    try_compile(
        "merge_pass",
        lambda h, lo: ps._merge_pass(
            h, lo, ps.T_OUT, ps.T_OUT, ps.BLKS, 2 * ps.T_OUT, False
        ),
        u32, u32,
    )
    for n in (8 * ps.T_OUT, 200_000_000):
        try_compile(
            f"sort_u64[n={n}]",
            lambda x: ps.sort_u64(x),
            sds((n,), jnp.uint64),
        )

    import dj_tpu
    from dj_tpu.core.table import Column, Table

    rows = 4 * 1024 * 1024
    i64 = sds((rows,), jnp.int64)
    tbl = Table((Column(i64, dj_tpu.dtypes.int64),
                 Column(i64, dj_tpu.dtypes.int64)))
    # Pin BOTH kernels explicitly: the runtime TPU default is
    # sort=pallas + expand=pallas, but this probe's host devices are
    # CPU, so relying on the platform default would silently lower
    # expand=hist and the evidence would not cover the device combo.
    os.environ["DJ_JOIN_SORT"] = "pallas"
    os.environ["DJ_JOIN_EXPAND"] = "pallas"
    try_compile(
        "inner_join[sort=pallas,expand=pallas]",
        lambda l, r: dj_tpu.inner_join(l, r, [0], [0], out_capacity=rows),
        tbl, tbl,
    )
    # The sort-isolating hardware A/B (suite2/r04b step 2) runs
    # sort=pallas WITH expand=hist — cover that lowering combination
    # too, so a bad interaction fails here on the CPU host instead of
    # burning a claim-window entry on the chip.
    os.environ["DJ_JOIN_EXPAND"] = "hist"
    try_compile(
        "inner_join[sort=pallas,expand=hist]",
        lambda l, r: dj_tpu.inner_join(l, r, [0], [0], out_capacity=rows),
        tbl, tbl,
    )


if __name__ == "__main__":
    main()
