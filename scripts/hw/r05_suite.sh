#!/usr/bin/env bash
# Round-5 suite #1 (chained after r04d_suite.sh on tunnel recovery):
#   1. Cascaded-codec GB/s + ratio at bench-scale buckets (VERDICT r4
#      missing #4) — the reference's go/no-go economics
#      (all_to_all_comm.cpp:471-477).
#   2. One real-scale TPC-H-style run (VERDICT r4 next-step #8):
#      ~50M lineitem x 12.5M orders on the chip, strings riding as
#      payload; falls back to half scale on failure.
# NO kill-timeouts (tunnel-wedge lesson, ROUND4_NOTES); every python
# entry self-watchdogs.
set -u
. "$(dirname "$0")/lib.sh"

# Append EVERY JSON line of an entry (codec emits one per case).
blog_each() {
    local name=$1
    grep '^{' "/tmp/hw/$name.out" 2>/dev/null | grep -v '"error"' \
        | while IFS= read -r line; do
        echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
             "\"tag\": \"$name\", \"bench\": $line}" >> BENCH_LOG.jsonl
    done
}

run 0 codec python -u scripts/hw/codec_bench.py
blog_each codec

if [ ! -f /tmp/tpch_r05/orders00.parquet ]; then
    run 0 tpch_gen python scripts/make_tpch_sample.py /tmp/tpch_r05 \
        --splits 1 --orders-per-split 12500000
fi
run 0 tpch env DJ_BENCH_WATCHDOG_S=2100 python -u benchmarks/tpch.py \
    --data-folder /tmp/tpch_r05 --bucket-factor 1.5 --out-factor 1.2 \
    --repeat 2 --json
if grep -q '^{' /tmp/hw/tpch.out; then
    blog_each tpch
else
    log "tpch full scale failed; trying half scale"
    run 0 tpch_gen_half python scripts/make_tpch_sample.py /tmp/tpch_r05h \
        --splits 1 --orders-per-split 6250000
    run 0 tpch_half env DJ_BENCH_WATCHDOG_S=2100 python -u benchmarks/tpch.py \
        --data-folder /tmp/tpch_r05h --bucket-factor 1.5 --out-factor 1.2 \
        --repeat 2 --json
    blog_each tpch_half
fi
log "R05 SUITE DONE"
