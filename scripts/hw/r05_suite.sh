#!/usr/bin/env bash
# Round-5 suite #1 (chained after r04d_suite.sh on tunnel recovery):
#   1. Cascaded-codec GB/s + ratio at bench-scale buckets (VERDICT r4
#      missing #4) — the reference's go/no-go economics
#      (all_to_all_comm.cpp:471-477).
#   2. One real-scale TPC-H-style run (VERDICT r4 next-step #8):
#      ~50M lineitem x 12.5M orders on the chip, strings riding as
#      payload; falls back to half scale on failure.
# NO kill-timeouts (tunnel-wedge lesson, ROUND4_NOTES); every python
# entry self-watchdogs.
set -u
. "$(dirname "$0")/lib.sh"

# Append EVERY JSON line of an entry (codec emits one per case).
blog_each() {
    local name=$1
    grep '^{' "/tmp/hw/$name.out" 2>/dev/null | grep -v '"error"' \
        | while IFS= read -r line; do
        echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
             "\"tag\": \"$name\", \"bench\": $line}" >> BENCH_LOG.jsonl
    done
}

# vfull qualification (round-5 build): vcarry's plan + in-kernel
# right-side resolution — zero output-sized gathers. Row-exact gate
# first (the MXU lesson), duplicate-heavy second shape, then bench.
# Standalone-run safety: the HIGH-precision gate normally comes from
# r04d's verify_high entry; if /tmp was wiped (reboot between
# sessions) OR the entry is rev-stale (promote.py would reject it
# anyway), re-run it here so the precision arm is never silently lost.
if [ "$(cat /tmp/hw/verify_high.rev 2>/dev/null)" \
     != "$(git rev-parse --short HEAD)" ]; then
    run 0 verify_high env DJ_VMETA_PRECISION=high \
        python -u scripts/hw/verify_join_rows.py 2000000
fi
run 0 verify_vfull env DJ_JOIN_EXPAND=pallas-vfull \
    python -u scripts/hw/verify_join_rows.py 2000000
run 0 verify_vfull_dups env DJ_JOIN_EXPAND=pallas-vfull \
    DJ_VERIFY_KMAX=20000 DJ_VERIFY_CAPX=60 \
    python -u scripts/hw/verify_join_rows.py 1000000
if grep -q "ROWS EXACT" /tmp/hw/verify_vfull.out \
   && grep -q "ROWS EXACT" /tmp/hw/verify_vfull_dups.out; then
    run 0 bench_vfull env DJ_JOIN_EXPAND=pallas-vfull python -u bench.py
    blog bench_vfull 100000000
    # Tighter output capacity: 31.9M slots vs 30M true matches is
    # still ~410 sigma of binomial headroom; every output-sized op
    # shrinks ~12% vs jof .33 (measured 5.90 vs 7.95 between .33/.45).
    run 0 bench_vfull_jof29 env DJ_JOIN_EXPAND=pallas-vfull \
        DJ_BENCH_JOF=0.29 python -u bench.py
    blog bench_vfull_jof29 100000000
    if grep -q "ROWS EXACT" /tmp/hw/verify_high.out 2>/dev/null; then
        run 0 verify_vfull_high env DJ_JOIN_EXPAND=pallas-vfull \
            DJ_VMETA_PRECISION=high \
            python -u scripts/hw/verify_join_rows.py 2000000
        if grep -q "ROWS EXACT" /tmp/hw/verify_vfull_high.out; then
            run 0 bench_vfull_high env DJ_JOIN_EXPAND=pallas-vfull \
                DJ_VMETA_PRECISION=high python -u bench.py
            blog bench_vfull_high 100000000
        fi
    fi
else
    log "SKIP bench_vfull (not row-exact)"
fi

# Standalone vfull kernel cost at bench shapes (what the margin
# eq-walk itself costs vs expand_values' ~1.1 s).
run 0 kernels_vfull python -u scripts/hw/residual_bench.py expand_vfull_S

run 0 codec python -u scripts/hw/codec_bench.py
blog_each codec

if [ ! -f /tmp/tpch_r05/orders00.parquet ]; then
    run 0 tpch_gen python scripts/make_tpch_sample.py /tmp/tpch_r05 \
        --splits 1 --orders-per-split 12500000
fi
run 0 tpch env DJ_BENCH_WATCHDOG_S=2100 python -u benchmarks/tpch.py \
    --data-folder /tmp/tpch_r05 --bucket-factor 1.5 --out-factor 1.2 \
    --repeat 2 --json
if grep -q '^{' /tmp/hw/tpch.out; then
    blog_each tpch
    # gpubdb-style shuffle at the same scale (reuses the lineitem
    # split; the reference's third benchmark axis). NOTE: on one chip
    # the shuffle takes the degenerate self-copy path, which skips
    # compression — codec economics come from the codec entry above;
    # this measures the drop-nulls + shuffle pipeline at scale.
    mkdir -p /tmp/gpubdb_r05
    ln -sf /tmp/tpch_r05/lineitem00.parquet /tmp/gpubdb_r05/
    run 0 gpubdb python -u benchmarks/gpubdb_shuffle_on.py \
        --data-folder /tmp/gpubdb_r05 \
        --columns L_ORDERKEY,L_PARTKEY,L_QUANTITY \
        --compression --bucket-factor 1.5 --out-factor 1.3 \
        --repeat 2 --json
    blog_each gpubdb
else
    log "tpch full scale failed; trying half scale"
    run 0 tpch_gen_half python scripts/make_tpch_sample.py /tmp/tpch_r05h \
        --splits 1 --orders-per-split 6250000
    run 0 tpch_half env DJ_BENCH_WATCHDOG_S=2100 python -u benchmarks/tpch.py \
        --data-folder /tmp/tpch_r05h --bucket-factor 1.5 --out-factor 1.2 \
        --repeat 2 --json
    blog_each tpch_half
fi
# Bucketed-sort crossover (armed by the single-trace plan PR): mono
# lax.sort vs the DJ_JOIN_SORT=bucketed two-pass at join shapes. CPU
# row-exactness is already proven in tests/test_join_plan.py; this A/B
# decides whether bucketed becomes the TPU default sort plan. If any
# case wins at the 200M headline size AND is exact, confirm end to end
# with a full bench run under the flag before considering a default
# flip.
run 0 sort_xover python -u scripts/hw/sort_bucket_crossover.py
blog_each sort_xover
# Gate: at least one case must WIN (speedup > 1.02) AND be exact.
if python - <<'EOF'
import json, sys
try:
    cases = [json.loads(l) for l in open("/tmp/hw/sort_xover.out")
             if l.startswith("{")]
except OSError:
    sys.exit(1)
sys.exit(0 if any(
    c.get("speedup", 0) > 1.02 and c.get("exact") for c in cases
) else 1)
EOF
then
    run 0 bench_bucketed env DJ_JOIN_SORT=bucketed python -u bench.py
    blog bench_bucketed 100000000
fi

# Default promotion: flip TPU_DEFAULT_EXPAND / DEFAULT_PRECISION to the
# best row-exact-qualified measured config and COMMIT, so the driver's
# scoring `python bench.py` runs it even if the tunnel recovered after
# the build session ended. Then re-confirm end to end under default env.
run 0 promote python -u scripts/hw/promote.py
if grep -q "^PROMOTED" /tmp/hw/promote.out; then
    run 0 bench_promoted python -u bench.py
    blog bench_promoted 100000000
    git add BENCH_LOG.jsonl measurements 2>/dev/null
    git commit -q -m "Record promoted-default bench confirmation" || true
fi
log "R05 SUITE DONE"
