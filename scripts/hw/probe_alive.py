"""Gentle TPU liveness probe: one client, one trivial op, then exit.

Run this BEFORE firing a hardware suite: if the tunnel is wedged
(see ROUND3_NOTES.md), each suite entry would burn its own ~35-min
watchdog window; this probe answers alive/dead with one claim. Never
kill it externally — the self-watchdog exits on its own (killing a
client mid-claim can wedge the tunnel).
"""

import os
import sys
import threading
import time

t0 = time.time()


def _bail():
    print(f"PROBE TIMEOUT after {time.time() - t0:.0f}s", flush=True)
    os._exit(3)


wd = threading.Timer(float(os.environ.get("PROBE_WATCHDOG_S", 2100)), _bail)
wd.daemon = True
wd.start()

print(f"probe start pid={os.getpid()}", flush=True)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

print(f"[{time.time() - t0:7.1f}s] jax imported", flush=True)
d = jax.devices()
print(f"[{time.time() - t0:7.1f}s] devices: {d}", flush=True)
x = np.asarray(jnp.arange(8) * 2)
print(f"[{time.time() - t0:7.1f}s] PROBE OK compute={x.tolist()}", flush=True)
sys.exit(0)
