"""On-chip cascaded-codec economics: GB/s + ratio at bench-scale buckets.

The reference prints compression ratio AND throughput at runtime and
treats "codec GB/s >> wire GB/s" as the go/no-go for the compressed
path (/root/reference/src/all_to_all_comm.cpp:471-477). The cascaded
codec here is correctness-tested and counter-instrumented, but its TPU
throughput at bench-scale buckets had never been measured — this script
answers whether the compressed inter-domain path can ever win on chip.

Per case: [n_peers, B] buckets, auto-selected options per content kind,
jitted compress_buckets / decompress_buckets, roundtrip-verified, then
best-of-3 wall clock. Emits one JSON line per case (suite's blog()
appends the last; the full set lands in measurements/).

Content kinds mirror the bench workload's columns:
  keys:    uniform int64 in [0, 2*rows) — bitpack-only territory.
  rowids:  per-partition row ids (arange slices) — delta+bp territory.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

_T0 = time.time()


def _bail():
    print(json.dumps({"metric": "codec_bench", "value": None,
                      "error": f"watchdog after {time.time()-_T0:.0f}s"}),
          flush=True)
    os._exit(3)


wd = threading.Timer(float(os.environ.get("DJ_BENCH_WATCHDOG_S", 2100)), _bail)
wd.daemon = True
wd.start()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import dj_tpu  # noqa: E402  (x64 on)
from dj_tpu.compress import cascaded as cz  # noqa: E402

N_PEERS = int(os.environ.get("DJ_CODEC_PEERS", 8))
B = int(os.environ.get("DJ_CODEC_BUCKET", 4_000_000))
WIRE_FACTOR = float(os.environ.get("DJ_CODEC_WIRE_FACTOR", 0.8))


def _sync(x):
    return np.asarray(x)  # block_until_ready doesn't sync the axon tunnel


def _case(name, host_data, opts=None, wire_factor=None):
    raw_bytes = host_data.size * 8
    if opts is None:
        # The production selector (permuted 100x1024 sample, slack 2.0)
        # — the same call generate_auto_select_compression_options makes.
        opts, wire_factor = cz.select_cascaded_options(host_data.reshape(-1))
    wire_factor = WIRE_FACTOR if wire_factor is None else wire_factor
    cap_words = cz.compressed_capacity_words(B * 8, wire_factor)
    buckets = jnp.asarray(host_data)

    comp_fn = jax.jit(
        lambda b: cz.compress_buckets(b, 8, opts, cap_words)
    )
    words, totals, ovf = comp_fn(buckets)
    totals_h = _sync(totals)
    assert not _sync(ovf).any(), f"{name}: wire capacity overflow"
    dec_fn = jax.jit(
        lambda w: cz.decompress_buckets(w, 8, opts, B, jnp.int64)
    )
    dec = dec_fn(words)
    np.testing.assert_array_equal(_sync(dec), host_data, err_msg=name)

    def best_of(fn, arg, iters=3):
        best = None
        for _ in range(iters):
            t0 = time.perf_counter()
            r = fn(arg)
            _sync(r[0] if isinstance(r, tuple) else r)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    t_c = best_of(comp_fn, buckets)
    t_d = best_of(dec_fn, words)
    wire_bytes = int(totals_h.sum()) * 8
    line = {
        "metric": f"codec_{name}",
        "value": round(raw_bytes / t_c / 1e9, 2),
        "unit": "compress GB/s (raw)",
        "decompress_gbps": round(raw_bytes / t_d / 1e9, 2),
        "ratio": round(raw_bytes / wire_bytes, 3),
        "opts": f"rle={opts.num_rles},delta={opts.num_deltas},bp={opts.use_bp}",
        "n_peers": N_PEERS,
        "bucket_rows": B,
    }
    print(json.dumps(line), flush=True)
    return line


def main():
    rng = np.random.default_rng(42)
    rows = N_PEERS * B
    # Shuffle-realistic content: what the inter-domain pre-shuffle
    # actually compresses is hash-partitioned (permuted) buckets.
    keys = rng.integers(0, 2 * rows, size=(N_PEERS, B)).astype(np.int64)
    _case("keys_uniform", keys)
    ids = rng.permutation(rows).astype(np.int64).reshape(N_PEERS, B)
    _case("rowids_permuted", ids)
    # Codec best case: sorted runs where RLE+delta shine — bounds the
    # codec's own speed independent of content entropy.
    sorted_ids = np.arange(rows, dtype=np.int64).reshape(N_PEERS, B)
    _case(
        "rowids_sorted",
        sorted_ids,
        opts=cz.CascadedOptions(num_rles=0, num_deltas=1, use_bp=True),
        wire_factor=0.2,
    )


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 - JSON contract on failure
        import traceback

        traceback.print_exc()
        print(json.dumps({"metric": "codec_bench", "value": None,
                          "error": f"{type(e).__name__}: {e}"[:400]}),
              flush=True)
        sys.exit(1)
