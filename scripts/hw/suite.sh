#!/usr/bin/env bash
# Sequential hardware measurement suite — ONE TPU process at a time,
# no kill-timeouts (killed clients wedge the tunnel). Logs to /tmp/hw/.
# Priority order: headline first, then phase attribution, then A/Bs.
set -u
cd /root/repo
mkdir -p /tmp/hw /tmp/jax_cache_tpu
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_tpu
log() { echo "[$(date +%H:%M:%S)] $*" >> /tmp/hw/suite.log; }

run() { # run <name> <cmd...>
    local name=$1; shift
    log "START $name"
    "$@" > "/tmp/hw/$name.out" 2> "/tmp/hw/$name.err"
    local rc=$?
    mkdir -p /root/repo/measurements
    cp "/tmp/hw/$name.out" "/root/repo/measurements/r04_$name.out" 2>/dev/null
    grep -v "^WARNING" "/tmp/hw/$name.err" | tail -40 \
        > "/root/repo/measurements/r04_$name.err" 2>/dev/null
    log "END $name rc=$rc last=$(tail -c 300 "/tmp/hw/$name.out" | tr '\n' ' ')"
}

blog() { # append a bench-log entry from a suite output file
    local name=$1 rows=$2
    local line
    line="$(tail -1 "/tmp/hw/$name.out" 2>/dev/null)"
    case "$line" in
        *'"error"'*) log "SKIP blog $name (error line)" ;;
        '{'*) echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
                   "\"rows\": $rows, \"tag\": \"$name\", \"bench\": $line}" \
                >> BENCH_LOG.jsonl ;;
    esac
}

# 0. Insurance headline: conservative slack (bucket 1.5 / jof 1.0) and
# the default odf OOM-fallback chain, so a slack assert or OOM can
# never zero out the round's only hardware window. The tuned config is
# entry #1.
run bench_safe env DJ_BENCH_BUCKET=1.5 DJ_BENCH_JOF=1.0 python -u bench.py
blog bench_safe 100000000

# 1. Headline bench, packed sort on (default), odf=1.
run bench_odf1_pack env DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_pack 100000000
# 2. Stage-split phase breakdown (same config).
run bench_phases env DJ_BENCH_PHASES=1 DJ_BENCH_ODF=1 python -u bench.py
# 3. Primitive microbench (odf=4 shapes; odf=1 resident set OOMs).
run phase_odf4 env DJ_PHASE_REPS=4 python -u scripts/phase_bench.py
# 4. Packed u64 sort at TRUE odf=1 merged size (200M post-trim).
run sort200m python -u - <<'PYEOF'
import time, jax, jax.numpy as jnp, numpy as np
S = 200_000_000
x = jax.random.bits(jax.random.PRNGKey(0), (S,), dtype=jnp.uint32).astype(jnp.uint64)
np.asarray(x[:1])
f = jax.jit(lambda v, k: jax.lax.sort(v + k.astype(jnp.uint64)))
for k in range(3):
    t0 = time.perf_counter()
    np.asarray(f(x, jnp.uint32(k))[:1])
    print(f"sort200m iter{k}: {time.perf_counter()-t0:.3f}s", flush=True)
PYEOF
# 5. A/B: pack off.
run bench_odf1_nopack env DJ_JOIN_PACK=0 DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_nopack 100000000
# 6. A/B: carry-payloads plan.
run bench_odf1_carry env DJ_JOIN_CARRY=1 DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_carry 100000000
# 6c. A/B: Pallas merge-path expansion kernel (compiled Mosaic — AOT
# lowering verified round 4; no check-vma knob needed outside
# interpret mode).
run bench_odf1_pallas env DJ_JOIN_EXPAND=pallas DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_pallas 100000000
# 6d. Mosaic feature probes. The fused/join kernel modes are
# INTERPRET-ONLY (no arbitrary in-VMEM gather in the TPU ISA —
# ARCHITECTURE.md "Mosaic lowering"), so they are not benched on
# hardware.
run probe_gather python -u scripts/hw/probe_gather.py
run probe_sort python -u scripts/hw/probe_sort.py
# 7. odf sweep (overlap directive: what odf buys on one chip).
run bench_odf2 env DJ_BENCH_ODF=2 python -u bench.py
blog bench_odf2 100000000
run bench_odf4 env DJ_BENCH_ODF=4 python -u bench.py
blog bench_odf4 100000000
run bench_odf8 env DJ_BENCH_ODF=8 python -u bench.py
blog bench_odf8 100000000
# 8. 10M quick point for the trend log.
run bench_10m env DJ_BENCH_ROWS=10000000 DJ_BENCH_ODF=1 python -u bench.py
blog bench_10m 10000000
# 9. CPU-mesh collective-path trend (no TPU involved).
run cpu_mesh env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -u scripts/cpu_mesh_bench.py
blog cpu_mesh 1000000
log "SUITE DONE"
