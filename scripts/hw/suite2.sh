#!/usr/bin/env bash
# Round-4 follow-up measurements: the Pallas merge-sort A/B. Waits for
# suite.sh's "SUITE DONE" marker (one TPU process at a time), then
# benches sort_u64 vs lax.sort and the full join with
# DJ_JOIN_SORT=pallas. Same logging/artifact conventions as suite.sh.
set -u
cd /root/repo
mkdir -p /tmp/hw /tmp/jax_cache_tpu
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_tpu
log() { echo "[$(date +%H:%M:%S)] $*" >> /tmp/hw/suite.log; }

while ! grep -q "SUITE DONE" /tmp/hw/suite.log 2>/dev/null; do
    sleep 30
done

run() {
    local name=$1; shift
    log "START $name"
    "$@" > "/tmp/hw/$name.out" 2> "/tmp/hw/$name.err"
    local rc=$?
    mkdir -p /root/repo/measurements
    cp "/tmp/hw/$name.out" "/root/repo/measurements/r04_$name.out" 2>/dev/null
    grep -v "^WARNING" "/tmp/hw/$name.err" | tail -40 \
        > "/root/repo/measurements/r04_$name.err" 2>/dev/null
    log "END $name rc=$rc last=$(tail -c 300 "/tmp/hw/$name.out" | tr '\n' ' ')"
}

blog() {
    local name=$1 rows=$2
    local line
    line="$(tail -1 "/tmp/hw/$name.out" 2>/dev/null)"
    case "$line" in
        *'"error"'*) log "SKIP blog $name (error line)" ;;
        '{'*) echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
                   "\"rows\": $rows, \"tag\": \"$name\", \"bench\": $line}" \
                >> BENCH_LOG.jsonl ;;
    esac
}

# 1. Standalone sort A/B at odf=4 and odf=1 merged sizes.
run sort_ab python -u scripts/hw/sort_bench.py
# 2. Full join with the Pallas sort ONLY (expansion pinned to hist so
# the A/B against bench_odf1_pack isolates the sort; the unset-env
# default is now pallas on TPU).
run bench_odf1_psort env DJ_JOIN_SORT=pallas DJ_JOIN_EXPAND=hist \
    DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_psort 100000000
# 3. Pallas sort + Pallas expansion together (the new TPU defaults).
run bench_odf1_psort_pexp env DJ_JOIN_SORT=pallas DJ_JOIN_EXPAND=pallas \
    DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_psort_pexp 100000000
log "SUITE2 DONE"
