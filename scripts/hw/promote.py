"""Hardware-gated default promotion: flip the TPU kernel-plan defaults
to the best row-exact-qualified measured config, and commit.

Run by r05_suite.sh AFTER the qualification entries so the scored
`python bench.py` (which the round driver runs with default env)
reproduces the best number even if the tunnel recovered after the
build session ended. Promotion policy (the MXU precision lesson —
ARCHITECTURE.md): a candidate config may become the default ONLY if

  1. its row-exact oracle entries printed ROWS EXACT on the chip
     (both verify shapes for an expand-mode change; the extra
     verify_*_high entry for a precision change), AND
  2. its bench entry measured strictly faster than the incumbent
     (bench_default from this same suite run, falling back to the
     round-4 recorded 5.90 s if that entry errored).

Edits the kernel-plan constants — ops/join.py TPU_DEFAULT_EXPAND and
ops/pallas_expand.py DEFAULT_PRECISION — plus bench.py's jof default
when its arm qualified with the same winning config, then commits.
Prints one line `PROMOTED expand=... precision=... value=...` or
`NO PROMOTION ...`.

Second knob (round 6): the prepared-join MERGE tier, adjudicated
THREE ways — xla vs pallas vs probe — in one transaction.
ops/join.py TPU_DEFAULT_MERGE flips to a candidate tier only if that
tier's merge_xover arm (scripts/hw/merge_crossover.py) measured
speedup > 1.02 AND exact at the headline size, AND its prepared bench
(bench_prepared_pallas / bench_prepared_probe) beat the XLA-tier
prepared bench; among qualifiers the fastest prepared bench wins —
the same two-gate protocol as the expand/precision promotion.
"""

import functools
import json
import os
import re
import subprocess
import sys

HW = "/tmp/hw"
REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
INCUMBENT_FALLBACK = 5.90  # round-4 measured default (BENCH_LOG.jsonl)


@functools.lru_cache(maxsize=1)
def _head_rev():
    return subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
        capture_output=True, text=True, check=True,
    ).stdout.strip()


def at_head(name):
    """The entry was measured at the revision being promoted: a
    row-exact pass at an OLDER rev says nothing about HEAD's kernels
    (stale /tmp/hw survives reboots and suite re-runs). Entries
    without a rev stamp are treated as stale."""
    try:
        with open(f"{HW}/{name}.rev") as f:
            return f.read().strip() == _head_rev()
    except OSError:
        return False


def bench_value(name):
    if not at_head(name):
        return None
    try:
        with open(f"{HW}/{name}.out") as f:
            line = f.read().strip().splitlines()[-1]
        d = json.loads(line)
        if d.get("error") or d.get("value") is None:
            return None
        return float(d["value"])
    except Exception:  # noqa: BLE001 - absent/garbled entry = ineligible
        return None


def rows_exact(name):
    if not at_head(name):
        return False
    try:
        with open(f"{HW}/{name}.out") as f:
            return "ROWS EXACT" in f.read()
    except OSError:
        return False


# candidate bench entry -> (expand default, precision default,
# required ROWS-EXACT verify entries)
CANDIDATES = {
    "bench_vmeta_high": ("pallas-vmeta", "high", ["verify_high"]),
    "bench_vcarry": ("pallas-vcarry", "highest",
                     ["verify_vcarry", "verify_vcarry_dups"]),
    "bench_vcarry_high": ("pallas-vcarry", "high",
                          ["verify_vcarry", "verify_vcarry_dups",
                           "verify_vcarry_high"]),
    "bench_vfull": ("pallas-vfull", "highest",
                    ["verify_vfull", "verify_vfull_dups"]),
    "bench_vfull_high": ("pallas-vfull", "high",
                         ["verify_vfull", "verify_vfull_dups",
                          "verify_vfull_high"]),
}


def edit_constant(path, pattern, replacement):
    """Returns True if the file changed (False = already promoted —
    suites may re-run with /tmp/hw intact, and the second pass must be
    a no-op, not a crash)."""
    with open(path) as f:
        src = f.read()
    new, n = re.subn(pattern, replacement, src, count=1)
    assert n == 1, f"constant not found in {path}: {pattern}"
    if new == src:
        return False
    with open(path, "w") as f:
        f.write(new)
    return True


class _EditTransaction:
    """All-or-nothing source edits: snapshot each file before its first
    edit, restore every snapshot on failure. Guards the unattended
    promotion against the half-edited tree an assert after the first
    edit_constant used to leave behind (ADVICE r5 item 4)."""

    def __init__(self):
        self._orig: dict[str, str] = {}
        self.changed_paths: list[str] = []

    @property
    def changed(self):
        return bool(self.changed_paths)

    def edit(self, path, pattern, replacement):
        """Returns edit_constant's own result: did THIS edit change the
        file (not whether the transaction as a whole has changes)."""
        if path not in self._orig:
            with open(path) as f:
                self._orig[path] = f.read()
        changed = edit_constant(path, pattern, replacement)
        if changed and path not in self.changed_paths:
            self.changed_paths.append(path)
        return changed

    def rollback(self):
        for path, src in self._orig.items():
            with open(path, "w") as f:
                f.write(src)


# CPU interpret-mode smoke: the row-exactness oracle for the kernel
# paths a promotion flips. Cheap relative to an unattended bad commit.
SMOKE_TESTS = ["tests/test_vcarry.py", "tests/test_vfull.py"]
MERGE_SMOKE_TESTS = ["tests/test_prepared.py", "tests/test_probe_join.py"]


def smoke_ok(tests=None):
    """Run the CPU interpret smoke suite against the EDITED tree; the
    promoted defaults must still be row-exact off-chip before the
    unattended commit."""
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", *(tests or SMOKE_TESTS)],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=1800,
    )
    if r.returncode != 0:
        sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
    return r.returncode == 0


def merge_xover_wins(impl="pallas"):
    """True iff the merge_xover entry at HEAD has a case for ``impl``
    with speedup > 1.02 AND exact at its LARGEST measured size (a
    small-S win that evaporates at the headline must not flip the
    default). Cases without an "impl" tag predate the probe arm and
    are pallas cases."""
    if not at_head("merge_xover"):
        return False
    try:
        with open(f"{HW}/merge_xover.out") as f:
            cases = [
                json.loads(line)
                for line in f
                if line.startswith("{")
            ]
    except OSError:
        return False
    cases = [
        c for c in cases
        if not c.get("error") and c.get("impl", "pallas") == impl
    ]
    if not cases:
        return False
    n_max = max(c["n"] for c in cases)
    return any(
        c["n"] == n_max and c.get("speedup", 0) > 1.02 and c.get("exact")
        for c in cases
    )


# Merge-tier candidates the three-way gate adjudicates: tier value ->
# its prepared bench entry (r06_suite.sh arms all three).
MERGE_CANDIDATES = {
    "pallas": "bench_prepared_pallas",
    "probe": "bench_prepared_probe",
}


def promote_merge():
    """Flip ops/join.py TPU_DEFAULT_MERGE to the winning tier — xla vs
    pallas vs probe adjudicated WITH NUMBERS in one transaction (see
    module docstring): a candidate qualifies only if its merge_xover
    arm measured speedup > 1.02 AND exact at the largest size AND its
    prepared bench beat the XLA tier's; among qualifiers the fastest
    prepared bench wins. Separate transaction + commit from the expand
    promotion so one failed knob never rolls back the other."""
    xla = bench_value("bench_prepared_xla")
    qualified = []
    for impl, entry in MERGE_CANDIDATES.items():
        if not merge_xover_wins(impl):
            continue
        v = bench_value(entry)
        if v is not None and xla is not None and v < xla:
            qualified.append((v, impl))
    if not qualified:
        print(
            f"NO MERGE PROMOTION (no tier passed both gates; "
            f"xla={xla}, "
            + ", ".join(
                f"{i}={bench_value(e)}"
                f"{'' if merge_xover_wins(i) else ' [xover gate failed]'}"
                for i, e in MERGE_CANDIDATES.items()
            )
            + ")"
        )
        return
    value, winner = min(qualified)
    txn = _EditTransaction()
    try:
        changed = txn.edit(
            os.path.join(REPO, "dj_tpu/ops/join.py"),
            r'TPU_DEFAULT_MERGE = "[a-z-]+"',
            f'TPU_DEFAULT_MERGE = "{winner}"',
        )
    except BaseException:
        txn.rollback()
        raise
    if not changed:
        print(f"MERGE PROMOTED {winner} value={value} (already in place)")
        return
    try:
        ok = smoke_ok(MERGE_SMOKE_TESTS)
    except BaseException:
        txn.rollback()
        raise
    if not ok:
        txn.rollback()
        print("NO MERGE PROMOTION (smoke tests failed; edits reverted)")
        return
    msg = (
        f"Promote prepared-join merge tier: TPU_DEFAULT_MERGE={winner}\n\n"
        f"Hardware-qualified by scripts/hw/promote.py: merge_xover "
        f"({winner} arm)\nspeedup > 1.02 AND exact at the headline "
        f"size, prepared bench {value:.3f} s vs\nXLA tier "
        f"{xla:.3f} s (three-way xla/pallas/probe gate, "
        f"measurements/r06_*)."
    )
    paths = [os.path.relpath(p, REPO) for p in txn.changed_paths]
    subprocess.run(
        ["git", "commit", "-m", msg, "--", *paths], cwd=REPO, check=True,
    )
    print(f"MERGE PROMOTED {winner} value={value}")


def main():
    incumbent = bench_value("bench_default")
    if incumbent is None:
        incumbent = INCUMBENT_FALLBACK
    best = None  # (value, expand, precision, entry)
    for entry, (expand, precision, verifies) in CANDIDATES.items():
        if not all(rows_exact(v) for v in verifies):
            continue
        v = bench_value(entry)
        if v is None:
            continue
        if best is None or v < best[0]:
            best = (v, expand, precision, entry)
    if best is None or best[0] >= incumbent:
        print(f"NO PROMOTION (incumbent {incumbent}; best {best})")
        return
    value, expand, precision, entry = best
    txn = _EditTransaction()
    try:
        txn.edit(
            os.path.join(REPO, "dj_tpu/ops/join.py"),
            r'TPU_DEFAULT_EXPAND = "[a-z-]+"',
            f'TPU_DEFAULT_EXPAND = "{expand}"',
        )
        txn.edit(
            os.path.join(REPO, "dj_tpu/ops/pallas_expand.py"),
            r'DEFAULT_PRECISION = "[a-z]+"',
            f'DEFAULT_PRECISION = "{precision}"',
        )
        # The tighter jof arm runs only under vfull AT DEFAULT (highest)
        # precision; a passing entry IS its qualification (bench.py
        # asserts overflow-free + exact total). Promote the bench
        # default so the driver's bare `python bench.py` scores the
        # winning capacity too — but ONLY when the winning config is
        # exactly the one jof29 was measured with (vfull@highest);
        # pairing it with a different precision winner would ship a
        # combination never benchmarked.
        jof_note = ""
        jof29 = bench_value("bench_vfull_jof29")
        if entry == "bench_vfull" and jof29 is not None and jof29 < value:
            txn.edit(
                os.path.join(REPO, "bench.py"),
                r'os\.environ\.get\("DJ_BENCH_JOF", [0-9.]+\)',
                'os.environ.get("DJ_BENCH_JOF", 0.29)',
            )
            jof_note = f", bench jof default -> 0.29 ({jof29:.3f} s)"
    except BaseException:
        # A failed second edit must not leave the first one in the tree.
        txn.rollback()
        raise
    if not txn.changed:
        print(f"PROMOTED expand={expand} precision={precision} "
              f"value={value} (already in place)")
        return
    try:
        ok = smoke_ok()
    except BaseException:
        # A hung/failed smoke run (e.g. TimeoutExpired) must not leave
        # the edited, unvalidated tree behind either.
        txn.rollback()
        raise
    if not ok:
        txn.rollback()
        print(f"NO PROMOTION (smoke tests failed for expand={expand} "
              f"precision={precision}; edits reverted)")
        return
    msg = (
        f"Promote TPU defaults: expand={expand}, precision={precision}"
        f"{jof_note}\n\n"
        f"Hardware-qualified by scripts/hw/promote.py: row-exact oracle\n"
        f"green on the chip for {CANDIDATES[entry][2]}, bench {entry} "
        f"measured {value:.3f} s\nvs incumbent {incumbent:.3f} s at the "
        f"100Mx100M headline (measurements/r05_*)."
    )
    # Pathspec-isolated commit: ONLY the files this promotion actually
    # edited are committed — whatever happens to be staged (or locally
    # modified) elsewhere in the unattended checkout stays out. `git
    # commit -- <paths>` records the working-tree content of exactly
    # those paths and leaves the rest of the index untouched.
    paths = [os.path.relpath(p, REPO) for p in txn.changed_paths]
    subprocess.run(
        ["git", "commit", "-m", msg, "--", *paths], cwd=REPO, check=True,
    )
    print(f"PROMOTED expand={expand} precision={precision} value={value}")


if __name__ == "__main__":
    main()
    promote_merge()
