"""Does pallas_scan.join_scans LOWER under real Mosaic? (no device)

Same method as probe_mosaic_lower.py: AOT-compile for a v5e topology on
the CPU host. Covers the standalone kernel at production scale and a
small shape, checking the SMEM carry chain, the lane/row shift scans,
and the two-plane key decode all pass Mosaic.

Run: env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu TPU_WORKER_HOSTNAMES=localhost \
      python scripts/hw/probe_scan_lower.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dj_tpu.utils import compat

TOPO = topologies.get_topology_desc("v5e:2x2", "tpu")
MESH = Mesh(TOPO.devices, ("d",))


def try_compile(name, fn, *args):
    wrapped = compat.shard_map(
        fn,
        mesh=MESH,
        in_specs=tuple(P() for _ in args),
        out_specs=jax.tree.map(lambda _: P(), jax.eval_shape(fn, *args)),
        check_vma=False,
    )
    try:
        jax.jit(wrapped).lower(*args).compile()
        print(f"PASS {name}", flush=True)
        return True
    except Exception as e:
        msg = str(e).replace("\n", " ")[:300]
        print(f"FAIL {name}: {type(e).__name__}: {msg}", flush=True)
        if os.environ.get("DJ_PROBE_TRACE"):
            import traceback

            traceback.print_exc()
        return False


def main():
    from dj_tpu.ops.pallas_scan import join_scans

    def sds(shape, dt):
        return jax.ShapeDtypeStruct(shape, dt)

    ok = True
    for name, L in (("small", 1 << 16), ("bench_100m", 100_000_000)):
        S = 2 * L
        tb = max(1, int(S).bit_length())
        ok &= try_compile(
            f"join_scans[{name}]",
            lambda sp, lc, rc, tb=tb, L=L: join_scans(
                sp, lc, rc, tag_bits=tb, L=L, R=L
            ),
            sds((S,), jnp.uint64),
            sds((), jnp.int32),
            sds((), jnp.int32),
        )

    # The vmeta kernel standalone at bench scale.
    from dj_tpu.ops.pallas_expand import expand_values

    S_big = 200_000_000
    n_out = 49_500_000
    ok &= try_compile(
        "expand_values[bench]",
        lambda csum, cnt, stag, rst: expand_values(
            csum, cnt, stag, rst, n_out
        ),
        sds((S_big,), jnp.int64),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
    )

    # Full inner_join with the fused scans + each expansion mode (the
    # candidate TPU default combinations after the hardware A/B).
    import dj_tpu
    from dj_tpu.core.table import Column, Table

    rows = 4 * 1024 * 1024
    i64 = sds((rows,), jnp.int64)
    tbl = Table((Column(i64, dj_tpu.dtypes.int64),
                 Column(i64, dj_tpu.dtypes.int64)))
    os.environ["DJ_JOIN_SCANS"] = "pallas"
    for expand in ("pallas-vfull", "pallas-vcarry", "pallas-vmeta",
                   "pallas", "hist"):
        os.environ["DJ_JOIN_EXPAND"] = expand
        ok &= try_compile(
            f"inner_join[scans=pallas,expand={expand}]",
            lambda l, r: dj_tpu.inner_join(l, r, [0], [0], out_capacity=rows),
            tbl, tbl,
        )

    # The FULL vcarry eligibility envelope (n_pay 2..3 compile with
    # the halved-span geometry; n_pay=4 exhausts VMEM in the XLA
    # fallback branch and must DEGRADE to vmeta — certifying the
    # degrade is exactly what the n_pay=4 case checks).
    for mode in ("pallas-vcarry", "pallas-vfull"):
        os.environ["DJ_JOIN_EXPAND"] = mode
        for n_pay in (2, 3, 4):
            cols = tuple(
                Column(i64, dj_tpu.dtypes.int64) for _ in range(1 + n_pay)
            )
            wide_tbl = Table(cols)
            ok &= try_compile(
                f"inner_join[{mode},n_pay={n_pay}]",
                lambda l, r: dj_tpu.inner_join(
                    l, r, [0], [0], out_capacity=rows
                ),
                wide_tbl, wide_tbl,
            )

    # expand_vfull standalone at the bench scale (the geometry that
    # must fit VMEM on the chip: 7 windows of span+margin+blk i32).
    from dj_tpu.ops.pallas_expand import expand_vfull

    ok &= try_compile(
        "expand_vfull[bench]",
        lambda csum, cnt, rst, p0, p1, kl, kh, mr: expand_vfull(
            csum, cnt, rst, (p0, p1), kl, kh, mr, n_out
        ),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((S_big,), jnp.int32),
        sds((), jnp.int32),
    )
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
