#!/usr/bin/env bash
# Round-4 session-2 hardware suite: the Pallas merge-sort A/B that the
# round-4 session-1 outage cut short. Unlike suite2.sh (which chains
# after suite.sh), this runs immediately and wraps EVERY entry in a
# hard `timeout` — the session-1 wedge (one gather microbench case,
# 2h20m, zero progress) showed an un-timed entry can burn a whole
# claim window. Same artifact conventions as suite.sh.
set -u
. "$(dirname "$0")/lib.sh"

# 1. Standalone sort A/B: 65M first (fast signal), then 200M.
run 1500 sort_ab_65m env DJ_SORT_BENCH_SIZES=65000000 \
    python -u scripts/hw/sort_bench.py
run 2700 sort_ab_200m env DJ_SORT_BENCH_SIZES=200000000 \
    python -u scripts/hw/sort_bench.py
# 2. Full join, Pallas sort only (expansion pinned to hist so the A/B
# against bench_odf1_pack isolates the sort).
run 2400 bench_odf1_psort env DJ_JOIN_SORT=pallas DJ_JOIN_EXPAND=hist \
    DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_psort 100000000
# 3. Pallas sort + Pallas expansion together (candidate TPU defaults).
run 2400 bench_odf1_psort_pexp env DJ_JOIN_SORT=pallas \
    DJ_JOIN_EXPAND=pallas DJ_BENCH_ODF=1 python -u bench.py
blog bench_odf1_psort_pexp 100000000
log "R04B SUITE DONE"
