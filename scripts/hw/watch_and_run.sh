#!/usr/bin/env bash
# Persistent TPU recovery watcher: retry the gentle liveness probe; the
# moment a claim succeeds, fire the full measurement suite ONCE.
# One TPU client at a time, no kill-timeouts (ROUND3_NOTES.md). Run
# detached: setsid nohup bash scripts/hw/watch_and_run.sh &
set -u
cd /root/repo
mkdir -p /tmp/hw
n=0
while true; do
    n=$((n + 1))
    echo "[$(date +%H:%M:%S)] probe attempt $n" >> /tmp/hw/watch.log
    if python -u scripts/hw/probe_alive.py >> /tmp/hw/watch.log 2>&1; then
        echo "[$(date +%H:%M:%S)] TPU ALIVE after $n attempts; firing suite" \
            >> /tmp/hw/watch.log
        bash scripts/hw/r04d_suite.sh
        echo "[$(date +%H:%M:%S)] suite finished" >> /tmp/hw/watch.log
        break
    fi
    sleep 180
done
