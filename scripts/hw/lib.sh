# Shared helpers for the hardware suite scripts. Source from any
# scripts/hw/*.sh driver:   . "$(dirname "$0")/lib.sh"
#
# run [timeout_s] name cmd...  — run one entry under a hard timeout
#   (a session-1 wedge burned a 2h20m claim window; every entry gets
#   one), teeing stdout/err to /tmp/hw and measurements/r04_<name>.*.
# blog name rows               — append the entry's trailing JSON line
#   to BENCH_LOG.jsonl unless it is an error line.
cd /root/repo
mkdir -p /tmp/hw /tmp/jax_cache_tpu
export JAX_COMPILATION_CACHE_DIR=/tmp/jax_cache_tpu

log() { echo "[$(date +%H:%M:%S)] $*" >> /tmp/hw/suite.log; }

run() {
    local tmo=$1 name=$2; shift 2
    log "START $name (timeout ${tmo}s)"
    # Rev stamp: promote.py refuses qualification entries measured at a
    # different revision than the HEAD it would promote (stale /tmp/hw
    # survives reboots and suite re-runs).
    git rev-parse --short HEAD > "/tmp/hw/$name.rev"
    timeout --kill-after=60 "$tmo" "$@" \
        > "/tmp/hw/$name.out" 2> "/tmp/hw/$name.err"
    local rc=$?
    mkdir -p /root/repo/measurements
    cp "/tmp/hw/$name.out" "/root/repo/measurements/r04_$name.out" 2>/dev/null
    grep -v "^WARNING" "/tmp/hw/$name.err" | tail -40 \
        > "/root/repo/measurements/r04_$name.err" 2>/dev/null
    log "END $name rc=$rc last=$(tail -c 300 "/tmp/hw/$name.out" | tr '\n' ' ')"
}

blog() {
    local name=$1 rows=$2
    local line
    line="$(tail -1 "/tmp/hw/$name.out" 2>/dev/null)"
    case "$line" in
        *'"error"'*) log "SKIP blog $name (error line)" ;;
        '{'*) echo "{\"rev\": \"$(git rev-parse --short HEAD)\"," \
                   "\"rows\": $rows, \"tag\": \"$name\", \"bench\": $line}" \
                >> BENCH_LOG.jsonl ;;
    esac
}
