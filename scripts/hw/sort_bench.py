"""Hardware sort-floor measurement: lax.sort on the packed operand.

Times lax.sort at the true odf=1 merged size (200M) and the odf=4
merged size (65M), uint64 values — the join's dominant single term.
The Pallas merge-sort arm this script A/B'd in round 4 measured 26%
SLOWER (1544 vs 1221 ms at 200M; VPU-bound in the Batcher network)
and was deleted in round 5 — ARCHITECTURE.md "The sort floor" carries
the measurement and the op-count floor argument.

Run on the chip: python scripts/hw/sort_bench.py
Env: DJ_SORT_BENCH_SIZES=200000000,65000000
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

SIZES = [
    int(s)
    for s in os.environ.get(
        "DJ_SORT_BENCH_SIZES", "65000000,200000000"
    ).split(",")
]
IMPLS = os.environ.get("DJ_SORT_BENCH_IMPLS", "xla").split(",")


def main():
    for n in SIZES:
        x = jax.random.bits(
            jax.random.PRNGKey(0), (n,), dtype=jnp.uint32
        ).astype(jnp.uint64) << jnp.uint64(17)
        np.asarray(x[:1])
        fns = {}
        if "xla" in IMPLS:
            fns["xla"] = jax.jit(lambda v, k: jax.lax.sort(v + k))
        for name, f in fns.items():
            try:
                # Keep and CALL the AOT executable: jit dispatch does
                # not reuse lower().compile() results, so discarding it
                # would compile the 200M program twice inside the
                # suite's hard timeout.
                t0 = time.perf_counter()
                fc = f.lower(x, jnp.uint64(0)).compile()
                compile_s = time.perf_counter() - t0
                f = fc
                out = f(x, jnp.uint64(0))
                np.asarray(out[:1])
                # Correctness spot check on first run (uint64 diff
                # wraps, so compare adjacent elements directly).
                head = np.asarray(out[:1_000_000])
                ok = bool(np.all(head[1:] >= head[:-1]))
                best = None
                for k in range(1, 4):
                    t0 = time.perf_counter()
                    np.asarray(f(x, jnp.uint64(k))[:1])
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                print(
                    json.dumps(
                        {
                            "metric": f"sort_u64_{name}_{n}",
                            "value": round(best, 4),
                            "unit": "s",
                            "ns_per_elem": round(best / n * 1e9, 3),
                            "compile_s": round(compile_s, 1),
                            "sorted_head_ok": ok,
                        }
                    ),
                    flush=True,
                )
            except Exception as e:
                print(
                    json.dumps(
                        {
                            "metric": f"sort_u64_{name}_{n}",
                            "value": None,
                            "error": f"{type(e).__name__}: {str(e)[:200]}",
                        }
                    ),
                    flush=True,
                )


if __name__ == "__main__":
    main()
