#!/usr/bin/env bash
# AOT-compile the 8-device distributed join for a v5e:2x4 topology using
# the LOCAL libtpu (no device, no tunnel) and report async-collective
# overlap evidence. Safe to run during a TPU outage.
set -u
cd /root/repo
env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
    JAX_PLATFORMS=cpu TPU_WORKER_HOSTNAMES=localhost \
    python -u scripts/aot_overlap.py "$@"
