#!/usr/bin/env bash
# Round-4 session-2 suite #2: A/B the two new kernels and the output
# slack, stepwise so each win is attributable:
#   1. fused scans alone            (DJ_JOIN_SCANS=pallas)
#   2. + vmeta expansion            (DJ_JOIN_EXPAND=pallas-vmeta)
#   3. + tight output slack         (DJ_BENCH_JOF=0.33)
#   4. standalone kernel microbenches at bench shapes
# Hard timeout around every entry (see r04b_suite.sh).
set -u
. "$(dirname "$0")/lib.sh"

# 1. Fused scans alone (expansion pinned to the session-1 default).
run 2400 bench_pscan env DJ_JOIN_SCANS=pallas DJ_JOIN_EXPAND=pallas \
    DJ_BENCH_ODF=1 python -u bench.py
blog bench_pscan 100000000
# 2. + vmeta expansion (the candidate new TPU default combination).
run 2400 bench_pscan_vmeta env DJ_JOIN_SCANS=pallas \
    DJ_JOIN_EXPAND=pallas-vmeta DJ_BENCH_ODF=1 python -u bench.py
blog bench_pscan_vmeta 100000000
# 3. + tight output slack: out_cap 36.3M vs 49.5M — every output-sized
# op (expansion + 4 gathers) scales with it; expected matches 30M
# leave ~20% headroom and the exact-count assert keeps it honest.
run 2400 bench_pscan_vmeta_jof33 env DJ_JOIN_SCANS=pallas \
    DJ_JOIN_EXPAND=pallas-vmeta DJ_BENCH_JOF=0.33 DJ_BENCH_ODF=1 \
    python -u bench.py
blog bench_pscan_vmeta_jof33 100000000
log "R04C SUITE DONE"
