#!/usr/bin/env bash
# Round-4 session-2 suite #3 (fires via watch_and_run after the tunnel
# recovers):
#   1. bench.py with DEFAULT env — the exact config the driver scores
#      (scans=pallas, expand=pallas-vmeta@HIGHEST, jof=0.33).
#   2. Row-exact qualification of DJ_VMETA_PRECISION=high on hardware.
#   3. If (2) printed ROWS EXACT, bench the high-precision variant —
#      HIGHEST costs ~6 MXU passes, HIGH ~3; candidate ~0.5 s saving.
# NO kill-timeouts here: killing a client mid-claim is what wedges the
# tunnel (ROUND3_NOTES/ROUND4_NOTES); every python entry self-watchdogs
# (bench.py) or is small (verify_join_rows).
set -u
. "$(dirname "$0")/lib.sh"

run 0 bench_default python -u bench.py
blog bench_default 100000000

run 0 verify_high env DJ_VMETA_PRECISION=high \
    python -u scripts/hw/verify_join_rows.py 2000000
if grep -q "ROWS EXACT" /tmp/hw/verify_high.out; then
    run 0 bench_vmeta_high env DJ_VMETA_PRECISION=high python -u bench.py
    blog bench_vmeta_high 100000000
else
    log "SKIP bench_vmeta_high (high precision not row-exact)"
fi

# vcarry qualification: payloads ride the sort; left payloads expand
# in-kernel; ONE stacked (key, right-pay) gather at rpos. Row-exact
# gate first (the MXU lesson), then bench.
run 0 verify_vcarry env DJ_JOIN_EXPAND=pallas-vcarry \
    python -u scripts/hw/verify_join_rows.py 2000000
# Duplicate-heavy second shape: ~50 matches/key, long runs.
run 0 verify_vcarry_dups env DJ_JOIN_EXPAND=pallas-vcarry \
    DJ_VERIFY_KMAX=20000 DJ_VERIFY_CAPX=60 \
    python -u scripts/hw/verify_join_rows.py 1000000
if grep -q "ROWS EXACT" /tmp/hw/verify_vcarry.out \
   && grep -q "ROWS EXACT" /tmp/hw/verify_vcarry_dups.out; then
    run 0 bench_vcarry env DJ_JOIN_EXPAND=pallas-vcarry python -u bench.py
    blog bench_vcarry 100000000
    if grep -q "ROWS EXACT" /tmp/hw/verify_high.out 2>/dev/null; then
        run 0 verify_vcarry_high env DJ_JOIN_EXPAND=pallas-vcarry \
            DJ_VMETA_PRECISION=high python -u scripts/hw/verify_join_rows.py 2000000
        if grep -q "ROWS EXACT" /tmp/hw/verify_vcarry_high.out; then
            run 0 bench_vcarry_high env DJ_JOIN_EXPAND=pallas-vcarry \
                DJ_VMETA_PRECISION=high python -u bench.py
            blog bench_vcarry_high 100000000
        fi
    fi
else
    log "SKIP bench_vcarry (not row-exact)"
fi

# Standalone kernel costs at bench shapes (jof 0.33 out sizing), both
# precisions — tells the NEXT optimization round what the two new
# kernels themselves cost.
run 0 kernels python -u scripts/hw/residual_bench.py \
    join_scans_S expand_values_S
run 0 gather_i32 python -u scripts/hw/residual_bench.py \
    rpack_gather_i32pair lpack_gather_i32quad
run 0 kernels_high env DJ_VMETA_PRECISION=high \
    python -u scripts/hw/residual_bench.py expand_values_S
log "R04D SUITE DONE"

# Round-5 additions chain once the qualification entries are in.
bash "$(dirname "$0")/r05_suite.sh"
