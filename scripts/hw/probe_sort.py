"""Probe: does jnp.sort lower inside a Mosaic TPU kernel, and how fast?

Gates a future Pallas merge-sort for the join's dominant phase: local
tile sorts + log(n/tile) merge passes would be ~1 HBM pass each vs the
XLA sort's many. Also times lax.sort on the same shapes for reference.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 32_768
NT = 64  # tiles per call


def kernel(x_ref, o_ref):
    i = pl.program_id(0)
    o_ref[:] = jnp.sort(x_ref[:])


@jax.jit
def tile_sort(x):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((NT * TILE,), jnp.uint32),
        grid=(NT,),
        in_specs=[pl.BlockSpec((TILE,), lambda i: (i,))],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
    )(x)


def main():
    x = jax.random.bits(jax.random.PRNGKey(0), (NT * TILE,), dtype=jnp.uint32)
    np.asarray(x[:1])
    t0 = time.perf_counter()
    out = tile_sort(x)
    np.asarray(out[:1])
    print(f"pallas tile-sort compile+run {time.perf_counter()-t0:.2f}s")
    o = np.asarray(out).reshape(NT, TILE)
    w = np.sort(np.asarray(x).reshape(NT, TILE), axis=1)
    np.testing.assert_array_equal(o, w)
    print("CORRECT")
    for name, f in (
        ("pallas tile-sort", tile_sort),
        ("lax.sort flat", jax.jit(lambda v: jax.lax.sort(v))),
    ):
        np.asarray(f(x)[:1])
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(x)[:1])
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        per = best / (NT * TILE) * 1e9
        print(f"{name}: {best*1e3:.1f} ms ({per:.2f} ns/elem)")


if __name__ == "__main__":
    main()
