"""Bucketed-sort crossover study: monolithic lax.sort vs the two-pass
range-bucketed sort (ops/join.py `_bucketed_sort`, DJ_JOIN_SORT=bucketed)
on the packed join operand.

The monolithic packed sort is the join's wall (ARCHITECTURE.md "The
sort floor": ~1/8 of HBM peak at 200M, roofline_frac 0.022 headline)
and nobody has measured whether Balkesen-style two-pass partitioned
sorting beats it on this chip. Hypothesis terms (all measured here):

- grouping pass: lax.sort keyed on a NARROW int32 bucket id (cheaper
  comparator than the u64 two-plane lexicographic compare) carrying
  the word;
- bucket pass: ONE batched [K, C] sort at log2(C) = log2(slack*S/K)
  merge depth instead of log2(S);
- linear extract/compact copies (dynamic slices + DUS, no gathers).

Emits one JSON line per case:
  {"metric": "sort_bucket_crossover", "n", "k", "slack", "mono_ms",
   "bucketed_ms", "speedup", "exact"}

CPU row-exactness is proven by tests/test_join_plan.py; THIS script is
the chip A/B that decides promotion (flip DJ_JOIN_SORT=bucketed as the
TPU default only if speedup > 1 at the headline size AND exact).

Run on the chip: python scripts/hw/sort_bucket_crossover.py
Env: DJ_SORT_XOVER_SIZES=65000000,200000000
     DJ_SORT_XOVER_KS=16,64,256
     DJ_SORT_XOVER_SLACK=1.5
     DJ_SORT_XOVER_REPEAT=3
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

SIZES = [
    int(s)
    for s in os.environ.get(
        "DJ_SORT_XOVER_SIZES", "65000000,200000000"
    ).split(",")
]
KS = [int(k) for k in os.environ.get("DJ_SORT_XOVER_KS", "16,64,256").split(",")]
SLACK = float(os.environ.get("DJ_SORT_XOVER_SLACK", "1.5"))
REPEAT = int(os.environ.get("DJ_SORT_XOVER_REPEAT", "3"))


def _time(fc, *args) -> float:
    """Median of REPEAT dispatch+sync timings of a compiled callable."""
    ts = []
    for _ in range(REPEAT):
        t0 = time.perf_counter()
        out = fc(*args)
        np.asarray(out[:1])  # axon tunnel: materialize to sync
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main():
    from dj_tpu.ops.join import _bucketed_sort

    # pad_frac = 0: every row valid. pad_frac = 0.33: the production
    # per-batch padding share (bucket_factor slack) — padding sentinels
    # must ride the tail without eating bucket capacity.
    pad_fracs = [
        float(f)
        for f in os.environ.get("DJ_SORT_XOVER_PAD", "0,0.33").split(",")
    ]
    for n in SIZES:
      for pad_frac in pad_fracs:
        # Join-shaped operand: (rel << tag_bits | tag) with rel
        # range-compressed to a bench-like span (key < 2n) — the
        # occupied word width the bucketed range partition reads is
        # rel_bits + tag_bits, NOT 64.
        tag_bits = max(1, int(n).bit_length())
        rel_bits = int(2 * n).bit_length()
        word_bits = min(64, rel_bits + tag_bits)
        key = jax.random.randint(
            jax.random.PRNGKey(0), (n,), 0, 2 * n, dtype=jnp.int64
        ).astype(jnp.uint64)
        x = (key << jnp.uint64(tag_bits)) | jnp.arange(n, dtype=jnp.uint64)
        if pad_frac:
            nvalid = int(n * (1 - pad_frac))
            x = jnp.where(
                jnp.arange(n) < nvalid, x, ~jnp.uint64(0)
            )
        np.asarray(x[:1])

        mono = jax.jit(lambda v: jax.lax.sort(v)).lower(x).compile()
        mono_out = mono(x)
        mono_ms = _time(mono, x) * 1e3

        for k in KS:
            try:
                f = jax.jit(
                    lambda v: _bucketed_sort(
                        v, nbuckets=k, slack=SLACK, word_bits=word_bits
                    )
                ).lower(x).compile()
                out = f(x)
                # Bit-exactness on a 1M sample + the extremes (a full
                # 200M host pull through the tunnel costs minutes).
                step = max(1, n // 1_000_000)
                exact = bool(
                    np.array_equal(
                        np.asarray(out[::step]), np.asarray(mono_out[::step])
                    )
                    and np.asarray(out[-1]) == np.asarray(mono_out[-1])
                )
                ms = _time(f, x) * 1e3
                print(json.dumps({
                    "metric": "sort_bucket_crossover",
                    "n": n, "k": k, "slack": SLACK,
                    "pad_frac": pad_frac,
                    "mono_ms": round(mono_ms, 1),
                    "bucketed_ms": round(ms, 1),
                    "speedup": round(mono_ms / ms, 3),
                    "exact": exact,
                }), flush=True)
            except Exception as e:  # noqa: BLE001 - sweep must finish
                print(json.dumps({
                    "metric": "sort_bucket_crossover",
                    "n": n, "k": k, "slack": SLACK,
                    "pad_frac": pad_frac,
                    "error": f"{type(e).__name__}: {e}"[:300],
                }), flush=True)


if __name__ == "__main__":
    main()
