"""Row-exact join verification on the real chip, one config per run.

The round-4 session-2 lesson: aggregate-total asserts pass while every
row is wrong (the MXU default-precision bug) — kernel configs must be
qualified with a ROW-level numpy oracle ON HARDWARE before promotion.
Compares the full (key, left payload, right payload) multiset.

Usage: python scripts/hw/verify_join_rows.py [rows]
Env:   DJ_JOIN_* / DJ_VMETA_PRECISION select the config under test.
Exit:  0 rows exact; 1 mismatch (prints first diffs).
"""

import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import dj_tpu
from dj_tpu.core.table import Column, Table


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    # DJ_VERIFY_KMAX shrinks the key domain: a duplicate-heavy
    # distribution makes the kernels' LE/delta masks span long runs —
    # the regime the exactness arguments must hold in on hardware.
    kmax = int(os.environ.get("DJ_VERIFY_KMAX", 3 * n // 2))
    rng = np.random.default_rng(0)
    lk = rng.integers(0, kmax, n)
    rk = rng.integers(0, kmax, n)
    lp = rng.integers(0, 1 << 40, n)
    rp = rng.integers(0, 1 << 40, n)
    lt = Table(
        (Column(jnp.asarray(lk), dj_tpu.dtypes.int64),
         Column(jnp.asarray(lp), dj_tpu.dtypes.int64))
    )
    rt = Table(
        (Column(jnp.asarray(rk), dj_tpu.dtypes.int64),
         Column(jnp.asarray(rp), dj_tpu.dtypes.int64))
    )
    cap_mult = float(os.environ.get("DJ_VERIFY_CAPX", 1.5))
    cap = max(1, int(cap_mult * n))
    f = jax.jit(
        lambda a, b: dj_tpu.inner_join(a, b, [0], [0], out_capacity=cap)
    )
    res, total = f(lt, rt)
    k = int(res.count())
    cols = [np.asarray(c.data)[:k] for c in res.columns]
    got = sorted(zip(*cols))
    by = collections.defaultdict(list)
    for kk, p in zip(rk, rp):
        by[kk].append(p)
    want = sorted(
        (kk, p, q) for kk, p in zip(lk, lp) for q in by.get(kk, ())
    )
    cfg = {
        k: os.environ.get(k)
        for k in ("DJ_JOIN_SCANS", "DJ_JOIN_EXPAND", "DJ_JOIN_SORT",
                  "DJ_VMETA_PRECISION")
    }
    if int(total) != len(want):
        print(f"TOTAL MISMATCH {int(total)} != {len(want)} cfg={cfg}")
        sys.exit(1)
    if got != want:
        bad = [i for i, (g, w) in enumerate(zip(got, want)) if g != w][:3]
        print(f"ROWS MISMATCH cfg={cfg} first bad: ")
        for i in bad:
            print("  got", got[i], "want", want[i])
        sys.exit(1)
    print(f"ROWS EXACT n={n} matches={len(want)} cfg={cfg}")


if __name__ == "__main__":
    main()
