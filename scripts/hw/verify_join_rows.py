"""Row-exact join verification on the real chip, one config per run.

The round-4 session-2 lesson: aggregate-total asserts pass while every
row is wrong (the MXU default-precision bug) — kernel configs must be
qualified with a ROW-level numpy oracle ON HARDWARE before promotion.
Compares the full (key, left payload, right payload) multiset.

Usage: python scripts/hw/verify_join_rows.py [rows]
Env:   DJ_JOIN_* / DJ_VMETA_PRECISION select the config under test.
Exit:  0 rows exact; 1 mismatch (prints first diffs).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

import dj_tpu
from dj_tpu.core.table import Column, Table


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    # DJ_VERIFY_KMAX shrinks the key domain: a duplicate-heavy
    # distribution makes the kernels' LE/delta masks span long runs —
    # the regime the exactness arguments must hold in on hardware.
    kmax = int(os.environ.get("DJ_VERIFY_KMAX", 3 * n // 2))
    rng = np.random.default_rng(0)
    lk = rng.integers(0, kmax, n)
    rk = rng.integers(0, kmax, n)
    lp = rng.integers(0, 1 << 40, n)
    rp = rng.integers(0, 1 << 40, n)
    lt = Table(
        (Column(jnp.asarray(lk), dj_tpu.dtypes.int64),
         Column(jnp.asarray(lp), dj_tpu.dtypes.int64))
    )
    rt = Table(
        (Column(jnp.asarray(rk), dj_tpu.dtypes.int64),
         Column(jnp.asarray(rp), dj_tpu.dtypes.int64))
    )
    cap_mult = float(os.environ.get("DJ_VERIFY_CAPX", 1.5))
    cap = max(1, int(cap_mult * n))
    f = jax.jit(
        lambda a, b: dj_tpu.inner_join(a, b, [0], [0], out_capacity=cap)
    )
    res, total = f(lt, rt)
    k = int(res.count())
    got = np.stack([np.asarray(c.data)[:k] for c in res.columns])

    # Vectorized numpy oracle: the duplicate-heavy config produces
    # ~50M match rows — a Python-tuple oracle would cost tens of GB
    # and minutes of Timsort inside an untimed claim window.
    order = np.argsort(rk, kind="stable")
    rk_s, rp_s = rk[order], rp[order]
    lo = np.searchsorted(rk_s, lk, side="left")
    hi = np.searchsorted(rk_s, lk, side="right")
    cnts = hi - lo
    want_total = int(cnts.sum())
    ridx = np.repeat(lo, cnts) + (
        np.arange(want_total) - np.repeat(np.cumsum(cnts) - cnts, cnts)
    )
    want = np.stack(
        [np.repeat(lk, cnts), np.repeat(lp, cnts), rp_s[ridx]]
    )

    def canon(m):
        return m[:, np.lexsort(m[::-1])]

    cfg = {
        kk: os.environ.get(kk)
        for kk in ("DJ_JOIN_SCANS", "DJ_JOIN_EXPAND",
                   "DJ_VMETA_PRECISION")
    }
    if int(total) != want_total:
        print(f"TOTAL MISMATCH {int(total)} != {want_total} cfg={cfg}")
        sys.exit(1)
    gc, wc = canon(got), canon(want)
    if gc.shape != wc.shape or not np.array_equal(gc, wc):
        bad = np.nonzero((gc != wc).any(axis=0))[0][:3]
        print(f"ROWS MISMATCH cfg={cfg} first bad: ")
        for i in bad:
            print("  got", gc[:, i], "want", wc[:, i])
        sys.exit(1)
    print(f"ROWS EXACT n={n} matches={want_total} cfg={cfg}")


if __name__ == "__main__":
    main()
