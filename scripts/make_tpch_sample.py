"""Generate synthetic TPC-H-like split parquet files for the drivers.

The reference assumes tpch-dbgen output; this repo has no dbgen, so this
script synthesizes statistically similar lineitem/orders splits (unique
o_orderkey per order, ~4 lineitems per order, string priority/status
payloads) and writes ``lineitem{NN}.parquet`` / ``orders{NN}.parquet``
in the layout benchmarks/tpch.py expects. Also usable as a quick
gpubdb-style input (any parquet files with int64 cols 0,1).

Usage: python scripts/make_tpch_sample.py OUT_DIR --splits 8 --orders-per-split 100000
"""

import argparse
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet

PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]


def make_split(split: int, n_orders: int, seed: int, lineitems_per_order: float):
    rng = np.random.default_rng(seed + split)
    base = split * n_orders
    o_orderkey = np.arange(base, base + n_orders, dtype=np.int64)
    rng.shuffle(o_orderkey)
    o_priority = pa.array(
        np.array(PRIORITIES)[rng.integers(0, len(PRIORITIES), n_orders)]
    )
    o_custkey = rng.integers(0, n_orders, n_orders).astype(np.int64)
    orders = pa.table(
        {
            "O_ORDERKEY": pa.array(o_orderkey),
            "O_CUSTKEY": pa.array(o_custkey),
            "O_ORDERPRIORITY": o_priority,
        }
    )

    n_items = rng.poisson(lineitems_per_order, n_orders)
    l_orderkey = np.repeat(o_orderkey, n_items)
    rng.shuffle(l_orderkey)
    n_li = l_orderkey.shape[0]
    lineitem = pa.table(
        {
            "L_ORDERKEY": pa.array(l_orderkey),
            "L_PARTKEY": pa.array(
                rng.integers(0, n_orders * 4, n_li).astype(np.int64)
            ),
            "L_QUANTITY": pa.array(rng.integers(1, 51, n_li).astype(np.int64)),
        }
    )
    return orders, lineitem


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("out_dir")
    p.add_argument("--splits", type=int, default=8)
    p.add_argument("--orders-per-split", type=int, default=100_000)
    p.add_argument("--lineitems-per-order", type=float, default=4.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    os.makedirs(args.out_dir, exist_ok=True)
    for i in range(args.splits):
        orders, lineitem = make_split(
            i, args.orders_per_split, args.seed, args.lineitems_per_order
        )
        pa.parquet.write_table(
            orders, os.path.join(args.out_dir, f"orders{i:02d}.parquet")
        )
        pa.parquet.write_table(
            lineitem, os.path.join(args.out_dir, f"lineitem{i:02d}.parquet")
        )
        print(
            f"split {i}: {orders.num_rows} orders, {lineitem.num_rows} lineitems"
        )


if __name__ == "__main__":
    main()
