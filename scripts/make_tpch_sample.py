"""Generate synthetic TPC-H-like split parquet files for the drivers.

The reference assumes tpch-dbgen output; this repo has no dbgen, so this
script synthesizes statistically similar lineitem/orders/customer splits
(unique o_orderkey per order, ~4 lineitems per order, ~10 orders per
customer, string priority/segment payloads) and writes
``lineitem{NN}.parquet`` / ``orders{NN}.parquet`` /
``customer{NN}.parquet`` in the layout benchmarks/tpch.py (and its
``--q3`` pipeline shape) expects. Also usable as a quick
gpubdb-style input (any parquet files with int64 cols 0,1).

Usage: python scripts/make_tpch_sample.py OUT_DIR --splits 8 --orders-per-split 100000
"""

import argparse
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet

PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]


def make_split(
    split: int,
    n_orders: int,
    seed: int,
    lineitems_per_order: float,
    n_customers: int,
    n_customers_total: int,
):
    rng = np.random.default_rng(seed + split)
    base = split * n_orders
    o_orderkey = np.arange(base, base + n_orders, dtype=np.int64)
    rng.shuffle(o_orderkey)
    o_priority = pa.array(
        np.array(PRIORITIES)[rng.integers(0, len(PRIORITIES), n_orders)]
    )
    # custkeys draw from the GLOBAL customer domain so the Q3 pipeline's
    # stage-1 join crosses splits like the real distribution does.
    o_custkey = rng.integers(0, n_customers_total, n_orders).astype(np.int64)
    orders = pa.table(
        {
            "O_ORDERKEY": pa.array(o_orderkey),
            "O_CUSTKEY": pa.array(o_custkey),
            "O_ORDERPRIORITY": o_priority,
        }
    )

    n_items = rng.poisson(lineitems_per_order, n_orders)
    l_orderkey = np.repeat(o_orderkey, n_items)
    rng.shuffle(l_orderkey)
    n_li = l_orderkey.shape[0]
    lineitem = pa.table(
        {
            "L_ORDERKEY": pa.array(l_orderkey),
            "L_PARTKEY": pa.array(
                rng.integers(0, n_orders * 4, n_li).astype(np.int64)
            ),
            "L_QUANTITY": pa.array(rng.integers(1, 51, n_li).astype(np.int64)),
        }
    )

    # Unique custkeys per split (split-striped like o_orderkey) — the
    # dim side of the Q3 shape in benchmarks/tpch.py --q3.
    c_custkey = np.arange(
        split * n_customers, (split + 1) * n_customers, dtype=np.int64
    )
    rng.shuffle(c_custkey)
    customer = pa.table(
        {
            "C_CUSTKEY": pa.array(c_custkey),
            "C_MKTSEGMENT": pa.array(
                np.array(SEGMENTS)[rng.integers(0, len(SEGMENTS), n_customers)]
            ),
        }
    )
    return orders, lineitem, customer


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("out_dir")
    p.add_argument("--splits", type=int, default=8)
    p.add_argument("--orders-per-split", type=int, default=100_000)
    p.add_argument("--lineitems-per-order", type=float, default=4.0)
    p.add_argument("--customers-per-split", type=int, default=None,
                   help="default orders-per-split // 10 (TPC-H's ratio)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    n_customers = (
        args.customers_per_split
        if args.customers_per_split is not None
        else max(1, args.orders_per_split // 10)
    )
    os.makedirs(args.out_dir, exist_ok=True)
    for i in range(args.splits):
        orders, lineitem, customer = make_split(
            i, args.orders_per_split, args.seed, args.lineitems_per_order,
            n_customers, n_customers * args.splits,
        )
        pa.parquet.write_table(
            orders, os.path.join(args.out_dir, f"orders{i:02d}.parquet")
        )
        pa.parquet.write_table(
            lineitem, os.path.join(args.out_dir, f"lineitem{i:02d}.parquet")
        )
        pa.parquet.write_table(
            customer, os.path.join(args.out_dir, f"customer{i:02d}.parquet")
        )
        print(
            f"split {i}: {orders.num_rows} orders, "
            f"{lineitem.num_rows} lineitems, {customer.num_rows} customers"
        )


if __name__ == "__main__":
    main()
