#!/usr/bin/env python3
"""djlint CLI: the repo-native static lint (dj_tpu/analysis/lint.py).

Runs every rule over the repo and exits nonzero on any violation.
Deliberately loads the lint engine STANDALONE from file — no dj_tpu
package import, no jax — so a full run stays under 5 seconds and can
gate every commit (ci/lint.sh wires it into ci/tier1.sh).

Usage:
  python scripts/djlint.py                 # lint the repo
  python scripts/djlint.py --list-rules    # rule inventory
  python scripts/djlint.py --rule host-sync --rule lock-discipline
  python scripts/djlint.py --root /path/to/checkout

Suppressions are PER-LINE annotations only (`# dj: host-sync-ok`,
`# dj: lock-ok`, `# dj: env-key-ok`) — there is no file- or
rule-level opt-out by design.
"""

import argparse
import importlib.util
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[1]


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=str(REPO),
                    help="repo root (default: this checkout)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    root = pathlib.Path(args.root)
    lint = _load(root / "dj_tpu" / "analysis" / "lint.py", "_djlint")
    if args.list_rules:
        for name, fn in lint.RULES:
            print(f"{name}: {fn.__doc__.strip().splitlines()[0]}")
        return 0
    t0 = time.perf_counter()
    violations = lint.run_lint(root, rules=args.rule)
    for v in violations:
        print(v)
    n_rules = len(args.rule or lint.RULES)
    print(
        f"djlint: {len(violations)} violation(s), {n_rules} rule(s), "
        f"{time.perf_counter() - t0:.2f}s",
        file=sys.stderr,
    )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
