#!/usr/bin/env bash
# Format/lint runner (the reference ships .clang-format + a
# run-clang-format.py wrapper; this is the Python-project analogue,
# driven by the [tool.ruff] config in pyproject.toml).
#
# Usage: scripts/run_format.sh [--fix]
set -euo pipefail
cd "$(dirname "$0")/.."

if ! command -v ruff >/dev/null 2>&1; then
    echo "ruff not installed in this environment; config lives in" \
         "pyproject.toml [tool.ruff] — run 'ruff check .' where available."
    # Fall back to a syntax sweep so CI still catches parse errors.
    python -m compileall -q dj_tpu benchmarks tests bench.py __graft_entry__.py
    exit 0
fi

if [[ "${1:-}" == "--fix" ]]; then
    ruff check --fix .
    ruff format .
else
    ruff check .
    ruff format --check .
fi
