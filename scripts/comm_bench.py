"""Communicator backend comparison on the virtual 8-device CPU mesh.

The reference chooses between UCX (fused epochs), UCX bounce-buffer
(chunked pipelining), and NCCL backends per interconnect; this
framework's analogues are XlaCommunicator (fused lax.all_to_all),
BufferedCommunicator (chunked sub-collectives), and RingCommunicator
(ppermute rounds). Real ICI relative costs are unmeasurable in this
1-chip environment; this script records the CPU-mesh TREND per backend
(same caveat as cpu_mesh_bench.py: step changes between revisions and
gross relative structure only), answering VERDICT r2's "no measurement
of when ring beats fused" at the only scale available. Shares
cpu_mesh_bench.py's harness so the two trend benches cannot drift.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python scripts/comm_bench.py
"""

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: dj_tpu package
sys.path.insert(0, _HERE)  # scripts/: cpu_mesh_bench (explicit, so
# `python -m` / non-script imports work too, not just direct invocation)

from cpu_mesh_bench import setup, timed_join  # noqa: E402  (platform set there)

ROWS = int(os.environ.get("DJ_COMM_BENCH_ROWS", 1_000_000))


def main():
    import dj_tpu
    from dj_tpu.parallel.communicator import (
        BufferedCommunicator,
        RingCommunicator,
        XlaCommunicator,
    )

    harness = setup(ROWS)
    for cls in (XlaCommunicator, BufferedCommunicator, RingCommunicator):
        config = dj_tpu.JoinConfig(
            over_decom_factor=2,
            bucket_factor=1.5,
            join_out_factor=0.8,
            communicator_cls=cls,
        )
        best = timed_join(*harness, config, iters=3)
        print(
            json.dumps(
                {
                    "metric": f"cpu_mesh_join_1m_8dev_{cls.__name__}",
                    "value": round(best, 4),
                    "unit": "s (CPU trend only, not TPU perf)",
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
