"""Convert split TPC-H .tbl files (pipe-delimited) to parquet.

Counterpart of the reference's conversion script
(/root/reference/scripts/tpch_to_parquet.py): tpch-dbgen emits
pipe-delimited rows with a trailing delimiter (hence the placeholder
column), and the drivers want one parquet file per split named like the
source split (``lineitem00`` -> ``lineitem00.parquet``).

Usage: python scripts/tpch_to_parquet.py <folder-with-split-tbl-files>
"""

import argparse
import os

import pyarrow as pa
import pyarrow.csv
import pyarrow.parquet

# TPC-H schema subset used by the join drivers; decimal/date columns are
# left to arrow's inference (the drivers only require the key columns to
# be int64 and any payloads to be fixed-width or string).
SCHEMAS = {
    "lineitem": {
        "names": [
            "L_ORDERKEY", "L_PARTKEY", "L_SUPPKEY", "L_LINENUMBER",
            "L_QUANTITY", "L_EXTENDEDPRICE", "L_DISCOUNT", "L_TAX",
            "L_RETURNFLAG", "L_LINESTATUS", "L_SHIPDATE", "L_COMMITDATE",
            "L_RECEIPTDATE", "L_SHIPINSTRUCT", "L_SHIPMODE", "L_COMMENT",
        ],
        "types": {
            "L_ORDERKEY": pa.int64(),
            "L_PARTKEY": pa.int64(),
            "L_SUPPKEY": pa.int64(),
            "L_LINENUMBER": pa.int32(),
            "L_RETURNFLAG": pa.string(),
            "L_LINESTATUS": pa.string(),
            "L_SHIPINSTRUCT": pa.string(),
            "L_SHIPMODE": pa.string(),
            "L_COMMENT": pa.string(),
        },
    },
    "orders": {
        "names": [
            "O_ORDERKEY", "O_CUSTKEY", "O_ORDERSTATUS", "O_TOTALPRICE",
            "O_ORDERDATE", "O_ORDERPRIORITY", "O_CLERK", "O_SHIPPRIORITY",
            "O_COMMENT",
        ],
        "types": {
            "O_ORDERKEY": pa.int64(),
            "O_CUSTKEY": pa.int64(),
            "O_ORDERSTATUS": pa.string(),
            "O_ORDERPRIORITY": pa.string(),
            "O_CLERK": pa.string(),
            "O_SHIPPRIORITY": pa.int32(),
            "O_COMMENT": pa.string(),
        },
    },
}


def convert_splits(folder: str, prefix: str) -> None:
    schema = SCHEMAS[prefix]
    # Trailing '|' on every dbgen row parses as one extra empty column.
    names = schema["names"] + ["TRAILER"]
    for fname in sorted(os.listdir(folder)):
        path = os.path.join(folder, fname)
        if (
            not fname.startswith(prefix)
            or fname.endswith(".parquet")
            or not os.path.isfile(path)
        ):
            continue
        table = pa.csv.read_csv(
            path,
            read_options=pa.csv.ReadOptions(column_names=names),
            parse_options=pa.csv.ParseOptions(delimiter="|"),
            convert_options=pa.csv.ConvertOptions(
                include_columns=schema["names"],
                column_types=schema["types"],
            ),
        )
        pa.parquet.write_table(table, path + ".parquet", compression="snappy")
        print(f"{path} -> {path}.parquet ({table.num_rows} rows)")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("path", help="folder containing split .tbl files")
    args = p.parse_args()
    convert_splits(args.path, "lineitem")
    convert_splits(args.path, "orders")


if __name__ == "__main__":
    main()
