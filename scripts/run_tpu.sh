#!/usr/bin/env bash
# Launcher for TPU pods — the analogue of the reference's
# benchmark/run_sample.sh (GPU/NIC affinity + UCX env): on TPU the
# transport tuning collapses into jax.distributed + mesh construction,
# so this script just wires the standard multi-host env and runs a
# driver on every host.
#
# Single host (or single chip):
#   scripts/run_tpu.sh benchmarks/distributed_join.py --json
# Multi-host pod slice (run on every host, e.g. via gcloud ssh --worker=all):
#   COORDINATOR=<host0-ip>:8476 NUM_PROC=<#hosts> PROC_ID=<this-host-idx> \
#   scripts/run_tpu.sh benchmarks/distributed_join.py --json
set -euo pipefail

if [[ -n "${COORDINATOR:-}" ]]; then
  export JAX_COORDINATOR_ADDRESS="$COORDINATOR"
  export JAX_NUM_PROCESSES="${NUM_PROC:?set NUM_PROC}"
  export JAX_PROCESS_ID="${PROC_ID:?set PROC_ID}"
fi
# CPU simulation fallback: DJ_SIM_DEVICES=8 runs without TPUs.
if [[ -n "${DJ_SIM_DEVICES:-}" ]]; then
  export JAX_PLATFORMS=cpu
  export XLA_FLAGS="--xla_force_host_platform_device_count=${DJ_SIM_DEVICES} ${XLA_FLAGS:-}"
else
  # Comm/compute overlap needs async all-to-all, which is OFF by
  # default in this XLA: without it the batched shuffles lower as
  # synchronous ops and odf pipelining buys nothing (AOT schedule
  # evidence: measurements/r04_aot_overlap_{sync,async}.json and
  # ARCHITECTURE.md "Comm/compute overlap").
  export LIBTPU_INIT_ARGS="${LIBTPU_INIT_ARGS:-} --xla_tpu_enable_async_all_to_all=true"
fi
exec python "$@"
