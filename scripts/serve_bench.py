"""Serving-loop latency bench: open/closed-loop QPS through the scheduler.

The ROADMAP's "heavy traffic" claim gets a measured trend line instead
of an adjective: drive N queries through dj_tpu.serve.QueryScheduler
against one resident PreparedSide on the virtual 8-device CPU mesh
(TPU numbers ride the hardware queue when the tunnel returns) and
report p50/p95/p99 latency computed from the flight recorder's
per-query ``serve`` events — the same event stream a production
operator reads, so the bench measures exactly what serving exposes.

Modes:
- closed loop (default): DJ_SERVE_BENCH_CLIENTS threads each submit
  their share of DJ_SERVE_BENCH_QUERIES back-to-back (submit ->
  result -> next), the classic fixed-concurrency driver.
- open loop (DJ_SERVE_BENCH_QPS > 0): submits arrive on a fixed-rate
  clock regardless of completions; overload surfaces as queue-full /
  deadline sheds instead of coordinated omission.

Prints ONE JSON line; ci/bench_log.sh appends it to BENCH_LOG.jsonl as
the ``serve_closed_loop`` trend entry (absolute numbers are host-CPU
noise; the revision-to-revision trend is the signal).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 200_000))
QUERIES = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 32))
CLIENTS = int(os.environ.get("DJ_SERVE_BENCH_CLIENTS", 4))
QPS = float(os.environ.get("DJ_SERVE_BENCH_QPS", 0.0))
DISTINCT_LEFTS = int(os.environ.get("DJ_SERVE_BENCH_LEFTS", 8))

# The percentiles come from the flight recorder's ring: size it to the
# whole run (serve + coalesce + shed events) BEFORE dj_tpu imports, or
# a large QUERIES sweep would silently truncate the sample to the
# newest DJ_OBS_RING (1024) events and bias the percentiles warm.
os.environ.setdefault("DJ_OBS_RING", str(max(4096, 4 * QUERIES)))


def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs), p)) if xs else None


def main():
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8; "
        f"got {jax.devices()}"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    build = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(build, np.arange(ROWS, dtype=np.int64))
    )
    # key_range declared over the full generator range: the prepared
    # anchors cover every probe table, so no query pays a
    # plan-mismatch re-prepare mid-bench (without it, probe keys above
    # the BUILD side's observed max demote every coalesced member to
    # the singleton re-prepare path — the first logged run showed
    # exactly that in its embedded build-cache counters).
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, 2 * ROWS - 1),
    )
    prep = dj_tpu.prepare_join_side(
        topo, right, rc, [0], config, left_capacity=ROWS
    )
    # Distinct left tables (distinct tenants, one plan signature) so
    # coalescing has real work to batch and nothing degenerates to a
    # repeated-buffer cache artifact.
    lefts = []
    for q in range(DISTINCT_LEFTS):
        probe = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(probe, np.arange(ROWS, dtype=np.int64))
            )
        )
    # Pre-pay the singleton compile so percentiles measure serving, not
    # one cold trace (the coalesced group sizes still compile inline —
    # that tail is part of what the bench reports).
    dj_tpu.warmup_prepared_join(topo, prep, lefts[0][0], lefts[0][1], [0],
                                config)
    obs.drain()

    sched = QueryScheduler(ServeConfig.from_env())
    errors: dict[str, int] = {}
    errlock = threading.Lock()

    def _run_one(i):
        lt, lc = lefts[i % DISTINCT_LEFTS]
        try:
            t = sched.submit(topo, lt, lc, prep, None, [0], None, config)
            t.result(timeout=600)
        except Exception as e:  # noqa: BLE001 - bench counts, never dies
            with errlock:
                k = type(e).__name__
                errors[k] = errors.get(k, 0) + 1

    t0 = time.perf_counter()
    if QPS > 0:
        # Open loop: fixed-rate arrivals; completions ride the worker.
        threads = []
        for i in range(QUERIES):
            th = threading.Thread(target=_run_one, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(1.0 / QPS)
        for th in threads:
            th.join(timeout=600)
        mode = "open_loop"
    else:
        # Every query runs even when QUERIES % CLIENTS != 0: the first
        # `rem` clients take one extra (a silent drop would corrupt
        # the logged queries/qps trend).
        base, rem = divmod(QUERIES, max(1, CLIENTS))
        starts = [
            c * base + min(c, rem) for c in range(max(1, CLIENTS) + 1)
        ]

        def _client(c):
            for i in range(starts[c], starts[c + 1]):
                _run_one(i)

        threads = [
            threading.Thread(target=_client, args=(c,), daemon=True)
            for c in range(max(1, CLIENTS))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        mode = "closed_loop"
    wall = time.perf_counter() - t0
    sched.close()

    serve_events = obs.events("serve")
    ok = [e["total_s"] for e in serve_events if e["outcome"] == "result"]
    coalesced = sum(
        1 for e in serve_events
        if e["outcome"] == "result" and e.get("coalesced")
    )
    print(
        json.dumps(
            {
                "metric": "serve_closed_loop_8dev",
                "value": round(_percentile(ok, 95) or -1.0, 4),
                "unit": "p95 s/query (CPU trend only, not TPU perf)",
                "mode": mode,
                "rows": ROWS,
                "queries": QUERIES,
                "clients": CLIENTS,
                "qps_submitted": round(QUERIES / wall, 3),
                "completed": len(ok),
                "coalesced": coalesced,
                "p50_s": round(_percentile(ok, 50) or -1.0, 4),
                "p95_s": round(_percentile(ok, 95) or -1.0, 4),
                "p99_s": round(_percentile(ok, 99) or -1.0, 4),
                "errors": errors,
                "pressure_level": sched.pressure_level,
            }
        )
    )


def _write_metrics():
    path = os.environ.get("DJ_BENCH_METRICS")
    if not path:
        return
    try:
        import dj_tpu.obs as obs

        obs.write_snapshot(path)
    except Exception as e:  # noqa: BLE001
        print(f"# metrics dump failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    try:
        main()
    finally:
        _write_metrics()
