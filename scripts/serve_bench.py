"""Serving-loop latency bench: open/closed-loop QPS through the scheduler.

The ROADMAP's "heavy traffic" claim gets a measured trend line instead
of an adjective: drive N queries through dj_tpu.serve.QueryScheduler
against one resident PreparedSide on the virtual 8-device CPU mesh
(TPU numbers ride the hardware queue when the tunnel returns) and
report p50/p95/p99 latency sourced from the
``dj_serve_latency_seconds`` histogram — the same never-evicting
aggregate a production scrape reads — with the flight recorder's
per-query ``serve`` events kept as an exact-sample CROSS-CHECK
(``p95_events_s``). Sourcing from the histogram removed the old
ring-sizing workaround: the ring may truncate under a large QUERIES
sweep, the histogram cannot. The stdout JSON also embeds the ``slo``
summary (deadline hit rate, heal/shed rates, forecast-error p95) so
every BENCH_LOG ``serve_closed_loop`` entry records whether the run
met its own serving objectives, not just how fast it went.

Modes:
- closed loop (default): DJ_SERVE_BENCH_CLIENTS threads each submit
  their share of DJ_SERVE_BENCH_QUERIES back-to-back (submit ->
  result -> next), the classic fixed-concurrency driver.
- open loop (DJ_SERVE_BENCH_QPS > 0): submits arrive on a fixed-rate
  clock regardless of completions; overload surfaces as queue-full /
  deadline sheds instead of coordinated omission.

Prints ONE JSON line; ci/bench_log.sh appends it to BENCH_LOG.jsonl as
the ``serve_closed_loop`` trend entry (absolute numbers are host-CPU
noise; the revision-to-revision trend is the signal). The closed-loop
and multi-tenant entries additionally embed a ``truth`` block
(DJ_OBS_TRUTH armed for the run — ISSUE 15): model/XLA reconciliation
quantiles, per-builder compiled peak HBM, the measured device sample
(null on the CPU mesh), and per-tenant byte totals.

Multi-tenant / join-index modes:
- ``--tenants N --tables M`` (DJ_SERVE_BENCH_TENANTS / _TABLES): the
  closed loop drives N tenants round-robin over M distinct build
  tables THROUGH a JoinIndexCache-backed scheduler (Table rights at
  submit; the cache owns the PreparedSides) — the fleet shape, with
  ``dj_index_*`` traffic in the output.
- ``--index-ab`` (DJ_SERVE_BENCH_INDEX_AB=1): A/B the cache against
  per-query preparation on the same workload and log the
  ``serve_index_ab`` entry — cache-on amortized per-query latency vs
  paying prepare_join_side per query.
- ``--heavy-hitter`` (DJ_SERVE_BENCH_HEAVY=1): the skew-adaptive A/B
  (``serve_skew_ab`` entry): a heavy-hitter probe stream against a
  small (dimension-table) build side, driven closed-loop through the
  scheduler twice — shuffle-only vs the adaptive planner armed
  (DJ_PLAN_ADAPT=1). The shuffle-only arm pays the hot destination's
  bucket_factor heal ladder and then serves every query through the
  widened modules; the adaptive arm's planner picks the plan the
  workload actually wants (broadcast for the fits-per-shard build
  side; DJ_SERVE_BENCH_FORCE_SALT=1 prices broadcast out to measure
  the salted loop instead). value = adaptive/shuffle-only p95 ratio
  (< 1 = adaptive wins); the entry carries ``plan_tier`` so
  bench_trend groups it apart from shuffle-only medians.
- ``--unique-shapes`` (DJ_SERVE_BENCH_UNIQUE=1): the shape-churn A/B
  (``serve_shape_churn_ab`` entry): every query a distinct row count
  (today's worst case for the per-exact-shape module cache), driven
  closed-loop twice — DJ_SHAPE_BUCKET off vs on — with per-arm
  compiled-module counts and ``dj_compile_seconds_total`` embedded,
  plus a same-shape reference arm and a direct row-exactness check.
  value = bucketed/unbucketed p95 ratio; the entry carries
  ``shape_bucket`` so bench_trend groups it apart from exact-shape
  medians.
- ``--autotune-ab`` (DJ_SERVE_BENCH_AUTOTUNE_AB=1): the per-signature
  autotuner A/B (``serve_autotune_ab`` entry, PR 16): two prepared
  streams — same-shape (one signature) and mixed (two signatures
  alternating) — each driven closed-loop through the scheduler twice,
  hand-tuned defaults vs DJ_AUTOTUNE=1, under the deploy protocol
  (one warm query per signature untimed; the tuned arm's candidate
  pricing + top-2 probes land exactly there, so the timed windows
  compare steady-state serving). The merge-bound prepared workload is
  one whose hand-tuned default (the xla merge) is WRONG — the tuner's
  probe-merge pick is the measured win. value = tuned/hand-tuned
  mixed-stream p95 ratio; the entry embeds the same-shape ratio, the
  per-arm tune counts (warm tunes == distinct signatures; zero tunes
  inside any timed window), a direct row-exactness verdict, and the
  ``autotuned`` grouping stamp bench_trend groups on.
- ``--prepared-tier-ab`` (DJ_SERVE_BENCH_PREPARED_TIER_AB=1): the
  prepared BUILD-tier A/B (``serve_prepared_tier_ab`` entry, PR 17):
  one build table served at the q_rows=rows/32 serving shape through
  three arms with per-arm prepared sides — shuffle-prepared
  (baseline), probe (shuffle-prepared + DJ_JOIN_MERGE=probe), and
  broadcast-prepared (tier forced at prepare; the per-query module
  traces zero collectives). value = broadcast/shuffle p95 ratio
  (acceptance bar <= 0.8), with a fresh-unprepared-join row-exactness
  verdict and the ``prepared_tier`` grouping stamp bench_trend
  groups on.
- ``--pipeline-ab`` (DJ_SERVE_BENCH_PIPELINE_AB=1): the multi-join
  pipeline A/B (``serve_pipeline_ab`` entry, PR 18): the Q3 shape
  (lineitem ⋈ orders ⋈ customer) served two ways — as ONE
  ``submit_pipeline`` query (device-resident intermediate, derived
  ranges, broadcast-elided dim stage) vs back-to-back independent
  ``submit`` joins (the intermediate comes home as a query result,
  pays fresh key-range probes and a full second shuffle). Per-query
  latency is driver-side submit→final-result wall time (a composed
  query is TWO serve events, so the serve histogram can't express
  it). value = pipeline/composed p95 ratio (acceptance bar < 0.8),
  with a row-exactness verdict and the ``pipeline`` grouping stamp
  bench_trend groups on.
- ``--obs-ab`` (DJ_SERVE_BENCH_OBS_AB=1): the full-observatory
  overhead A/B (``serve_obs_overhead_ab`` entry, PR 19): the prepared
  closed loop served twice through per-arm schedulers — obs fully OFF
  vs the FULL observatory armed (obs + DJ_OBS_SKEW=1 + DJ_HLO_AUDIT=1
  + the DJ_OBS_BLACKBOX crash bundle). Latency is driver-side
  wall-clock per query (the off arm has no histogram by
  construction). value = on/off p95 ratio; acceptance bar < 1.05 —
  the observatory's standing claim that telemetry is host-side and
  off the query path, now measured closed-loop instead of asserted.
- ``--fleet K`` (DJ_SERVE_BENCH_FLEET=K): the fleet-coordination A/B
  (``serve_fleet_ab`` entry, PR 20): K worker PROCESSES each serve the
  same three-signature workload through index-backed schedulers twice
  — uncoordinated (every worker pays every signature's prepare:
  3K total, 2K duplicates) vs coordinated (DJ_FLEET_DIR armed: shared
  manifest + advisory leases make each signature ONE fleet-wide build;
  peers defer and serve unprepared, so duplicate prepares drop to 0).
  value = coordinated/uncoordinated pooled p95 ratio. The entry also
  carries the in-process tenant-flood arm's ``flood_shed_share``: with
  DJ_FLEET_TENANT_WEIGHTS set and the pressure ladder engaged, a
  polite tenant arriving at a queue full of a flooding tenant's work
  admits by shedding the flooder's newest tickets — the flood tenant
  must absorb >= 80% of the sheds. ``--fleet-worker`` is the internal
  child-process entry (one worker's serve loop; prints one JSON line
  the parent pools).
- ``--trace-out PATH`` (DJ_SERVE_BENCH_TRACE_OUT=path): after any
  arm, export the newest stored query timeline as Chrome trace-event
  JSON (``obs.export_trace`` — the ``/tracez`` payload) to PATH: a
  bench run leaves a Perfetto-loadable artifact of one real served
  query next to its JSON line.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _cli_int(flag, env, default):
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return int(os.environ.get(env, default))


INDEX_AB = "--index-ab" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_INDEX_AB")
)
HEAVY = "--heavy-hitter" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_HEAVY")
)
UNIQUE = "--unique-shapes" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_UNIQUE")
)
AUTOTUNE_AB = "--autotune-ab" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_AUTOTUNE_AB")
)
PREPARED_TIER_AB = "--prepared-tier-ab" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_PREPARED_TIER_AB")
)
PIPELINE_AB = "--pipeline-ab" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_PIPELINE_AB")
)
OBS_AB = "--obs-ab" in sys.argv or bool(
    os.environ.get("DJ_SERVE_BENCH_OBS_AB")
)
FLEET_K = _cli_int("--fleet", "DJ_SERVE_BENCH_FLEET", 0)
FLEET_WORKER = "--fleet-worker" in sys.argv
TRACE_OUT = (
    sys.argv[sys.argv.index("--trace-out") + 1]
    if "--trace-out" in sys.argv
    else os.environ.get("DJ_SERVE_BENCH_TRACE_OUT")
)
ROWS = int(
    os.environ.get("DJ_SERVE_BENCH_ROWS", 100_000 if INDEX_AB else 200_000)
)
QUERIES = int(
    os.environ.get("DJ_SERVE_BENCH_QUERIES", 16 if INDEX_AB else 32)
)
CLIENTS = int(os.environ.get("DJ_SERVE_BENCH_CLIENTS", 4))
QPS = float(os.environ.get("DJ_SERVE_BENCH_QPS", 0.0))
DISTINCT_LEFTS = int(os.environ.get("DJ_SERVE_BENCH_LEFTS", 8))
TENANTS = _cli_int("--tenants", "DJ_SERVE_BENCH_TENANTS", 2 if INDEX_AB else 1)
TABLES = _cli_int("--tables", "DJ_SERVE_BENCH_TABLES", 2 if INDEX_AB else 1)

def _percentile(xs, p):
    return float(np.percentile(np.asarray(xs), p)) if xs else None


def _round(v, nd=4):
    return None if v is None else round(v, nd)


def _hist_latency():
    """p50/p95/p99 + completed count from the
    ``dj_serve_latency_seconds{outcome="result"}`` histogram (tenants
    aggregated). The histogram never evicts, so no ring sizing is
    needed regardless of QUERIES; bucket-resolution estimates are the
    trade, which the serve-event cross-check in the output bounds."""
    from dj_tpu.obs import metrics as M

    raw = M.histogram_raw("dj_serve_latency_seconds", outcome="result")
    qs = {
        p: M.histogram_quantile(
            "dj_serve_latency_seconds", p / 100.0, outcome="result"
        )
        for p in (50, 95, 99)
    }
    return qs, (raw[3] if raw is not None else 0)


def _slo_summary(sched):
    """The SLO block every serve_closed_loop BENCH_LOG entry embeds:
    the driven scheduler's own sliding-window rates (its snapshot —
    the dj_slo_* gauges are labeled per scheduler) + the process-wide
    forecast-drift p95."""
    from dj_tpu.obs import metrics as M

    slo = dict(sched.snapshot()["slo"])
    slo.pop("window_terminals", None)
    slo["forecast_error_p95"] = _round(
        M.histogram_quantile("dj_forecast_error_ratio", 0.95)
    )
    slo["drift_events"] = int(M.counter_value("dj_forecast_drift_total"))
    return slo


def _observatory_summary():
    """The skew + roofline blocks each BENCH_LOG entry embeds next to
    the SLO summary (PR 9): measured partition-skew aggregates (empty
    batches=0 unless DJ_OBS_SKEW=1 armed the probe — ci/bench_log.sh
    arms it), the wire-matrix total, and the per-phase
    seconds/roofline-fraction view."""
    from dj_tpu.obs import roofline as obs_roofline
    from dj_tpu.obs import skew as obs_skew

    sk = dict(obs_skew.summary())
    sk["wire_total_bytes"] = obs_skew.wire_matrix()["total_bytes"]
    return sk, obs_roofline.summary()


def _arm_truth():
    """Arm the measured-truth layer (ISSUE 15) for the trend entries:
    every module the run compiles reports XLA cost/memory truth, and
    modules compiling inside a dispatch reconcile the admission
    forecast into dj_model_xla_ratio. setdefault, so an operator's
    explicit DJ_OBS_TRUTH=0 wins."""
    os.environ.setdefault("DJ_OBS_TRUTH", "1")


def _truth_block():
    """The `truth` block each serve_closed_loop / serve_multi_tenant
    BENCH_LOG entry embeds (ci/bench_log.sh documents it): model/XLA
    reconciliation quantiles, per-builder compiled peaks, the measured
    HBM sample (null on stat-less backends — the CPU mesh), and
    per-tenant byte totals. scripts/bench_trend.py reads only
    metric/value/grouping keys, so the block rides the envelope
    without perturbing any trend group."""
    from dj_tpu.obs import truth as obs_truth

    return obs_truth.truth_summary()


def _truth_armed():
    """The `truth_armed` grouping stamp (bench_trend): arming
    DJ_OBS_TRUTH pays one extra lower+compile per fresh IN-WINDOW
    module signature (measured ~2.7x closed-loop p95 on the 1-CPU CI
    host, where the coalesced group modules compile inside the
    measured window), so armed entries form their own trend group and
    never regress-compare against unarmed medians — the plan_tier /
    shape_bucket precedent."""
    from dj_tpu import knobs

    return bool(knobs.read_bool("DJ_OBS_TRUTH"))


def _mt_workload(dj_tpu, T, topo, rng):
    """TABLES distinct build tables (same schema — the join-index
    cache's dataset-identity keying is what keeps them apart) + the
    shared probe tables."""
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, 2 * ROWS - 1),
    )
    builds = []
    for m in range(TABLES):
        bk = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
        builds.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(bk, np.arange(ROWS, dtype=np.int64))
            )
        )
    lefts = []
    for q in range(max(2, DISTINCT_LEFTS // 2)):
        pk = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(pk, np.arange(ROWS, dtype=np.int64))
            )
        )
    return config, builds, lefts


def index_ab():
    """Cache-on vs per-query prepare on the same multi-tenant workload
    (the ``serve_index_ab`` BENCH_LOG entry). Per-query prepare is the
    no-cache fleet's honest baseline: every query re-pays the build
    side's shuffle+sort (compiles warmed for both arms first, so the
    A/B measures execution, not trace)."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    config, builds, lefts = _mt_workload(dj_tpu, T, topo, rng)

    # Warm every compile both arms will use (prepare + prepared query).
    warm_prep = dj_tpu.prepare_join_side(
        topo, builds[0][0], builds[0][1], [0], config, left_capacity=ROWS
    )
    dj_tpu.warmup_prepared_join(
        topo, warm_prep, lefts[0][0], lefts[0][1], [0], config
    )
    del warm_prep

    def _queries():
        for i in range(QUERIES):
            yield (
                f"tenant{i % TENANTS}",
                builds[i % TABLES],
                lefts[i % len(lefts)],
            )

    # Arm B: per-query prepare — what a fleet without the index pays.
    t0 = time.perf_counter()
    for _, (bt, bc), (lt, lc) in _queries():
        prep = dj_tpu.prepare_join_side(
            topo, bt, bc, [0], config, left_capacity=ROWS
        )
        _, counts, _ = dj_tpu.distributed_inner_join(
            topo, lt, lc, prep, None, [0], None, config
        )
        np.asarray(counts)
    per_query_prepare_s = (time.perf_counter() - t0) / QUERIES

    # Arm A: the join-index cache behind the scheduler — first query
    # per (tenant, table) pays the prepare, the rest hit. Coalescing
    # is OFF: each distinct group size compiles its own module, and a
    # 16-query A/B would spend its whole window tracing coalesced
    # variants arm B never pays — the serve_closed_loop entry already
    # trends coalescing; this entry isolates prepare amortization.
    obs.reset(reenable=True)
    obs.drain()
    cache = dj_tpu.JoinIndexCache()
    t0 = time.perf_counter()
    with QueryScheduler(
        ServeConfig(coalesce=False), worker=False, index=cache
    ) as s:
        tickets = [
            s.submit(topo, lt, lc, bt, bc, [0], [0], config, tenant=tn)
            for tn, (bt, bc), (lt, lc) in _queries()
        ]
        for t in tickets:
            t.result(timeout=600)
    cache_on_s = (time.perf_counter() - t0) / QUERIES
    hits = int(obs.counter_value("dj_index_hit_total"))
    misses = int(obs.counter_value("dj_index_miss_total"))
    cache.clear(force=True)
    print(
        json.dumps(
            {
                "metric": "serve_index_ab",
                "value": round(cache_on_s / per_query_prepare_s, 4),
                "unit": "cache-on/per-query-prepare amortized s ratio "
                        "(<1 = cache wins; CPU trend only)",
                "rows": ROWS,
                "queries": QUERIES,
                "tenants": TENANTS,
                "tables": TABLES,
                "cache_on_per_query_s": round(cache_on_s, 4),
                "per_query_prepare_s": round(per_query_prepare_s, 4),
                "index_hits": hits,
                "index_misses": misses,
            }
        )
    )


def heavy_hitter_ab():
    """Adaptive planner on vs shuffle-only on a heavy-hitter closed
    loop (the ``serve_skew_ab`` BENCH_LOG entry; module docstring has
    the design). Both arms run UNPREPARED submits (Table right, no
    index) — the adaptive tiers are unprepared-plan decisions — with
    identical workloads, fresh ledger/pins/registry per arm."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.serve import QueryScheduler, ServeConfig

    rows = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 100_000))
    queries = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 24))
    hot_keys = int(os.environ.get("DJ_SERVE_BENCH_HOT_KEYS", 2))
    hot_fraction = float(os.environ.get("DJ_SERVE_BENCH_HOT_FRAC", 0.6))
    # The classic heavy-hitter shape: a big probe stream against a
    # much smaller build (dimension) table. The salted copies
    # replicate SMALL build partitions; the shuffle plan's heal ladder
    # doubles the BIG probe buckets (and the join output capacity with
    # them) for every destination to fix the one hot one.
    build_rows = int(
        os.environ.get("DJ_SERVE_BENCH_BUILD_ROWS", max(1024, rows // 8))
    )

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    key_hi = 4 * build_rows
    # Build side: unique keys (the serving shape — skew lives in the
    # probe distribution, not the join output).
    rk = rng.permutation(key_hi)[:build_rows].astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(build_rows, dtype=np.int64))
    )
    hot = rk[:hot_keys].copy()  # hot keys that DO match build rows
    lefts = []
    for q in range(DISTINCT_LEFTS):
        lk = rng.integers(0, key_hi, rows).astype(np.int64)
        mask = rng.random(rows) < hot_fraction
        lk[mask] = hot[rng.integers(0, hot_keys, int(mask.sum()))]
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(lk, np.arange(rows, dtype=np.int64))
            )
        )
    # Tight factors: exactly the sizing the hot destination breaks on
    # the shuffle plan (its heal ladder widens EVERY bucket — part of
    # what the A/B measures) and the salted plan serves without
    # healing.
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=2.0,
        key_range=(0, key_hi - 1),
    )

    # The bench rewrites the planner knobs per arm; the OPERATOR'S own
    # values (e.g. a hand-set DJ_BROADCAST_BYTES steering the adaptive
    # arm's decision) must survive into the adaptive arm and out of
    # the process — save them once, restore rather than pop.
    ambient = {
        k: os.environ.get(k)
        for k in ("DJ_PLAN_ADAPT", "DJ_BROADCAST_BYTES")
    }

    def _restore(key):
        if ambient[key] is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = ambient[key]

    def _arm(adaptive: bool):
        # Fresh serving state per arm: learned factors, plan
        # decisions, tier pins, and the latency histogram must not
        # leak across arms.
        dj_ledger.reset()
        resil.reset_pins()
        obs.reset(reenable=True)
        obs.drain()
        if adaptive:
            os.environ["DJ_PLAN_ADAPT"] = "1"
            # By default the planner decides freely under the
            # operator's ambient knobs — for the dimension-table
            # heavy-hitter shape it picks BROADCAST (the small build
            # side fits per-shard HBM, and no destination exists to
            # be hot). DJ_SERVE_BENCH_FORCE_SALT=1 prices the
            # broadcast tier out so the entry measures the salted
            # loop instead (the entry's plan_tier names which tier
            # actually ran either way).
            _restore("DJ_BROADCAST_BYTES")
            if os.environ.get("DJ_SERVE_BENCH_FORCE_SALT"):
                os.environ["DJ_BROADCAST_BYTES"] = "0"
        else:
            os.environ.pop("DJ_PLAN_ADAPT", None)
            os.environ.pop("DJ_BROADCAST_BYTES", None)
        errors: dict[str, int] = {}
        errlock = threading.Lock()
        sched = QueryScheduler(ServeConfig.from_env())

        def _run_one(i):
            lt, lc = lefts[i % DISTINCT_LEFTS]
            try:
                t = sched.submit(topo, lt, lc, right, rc, [0], [0], config)
                t.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with errlock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1

        # Warm one query untimed: both arms pay their first-query
        # trace (and the shuffle arm its heal ladder) outside the
        # timed window, so the percentiles compare steady-state
        # serving — the fleet shape where one signature serves many
        # queries.
        _run_one(0)
        obs.reset(reenable=True)
        t0 = time.perf_counter()
        base, rem = divmod(queries, max(1, CLIENTS))
        starts = [c * base + min(c, rem) for c in range(max(1, CLIENTS) + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(max(1, CLIENTS))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        qs, completed = _hist_latency()
        heals = int(obs.counter_value("dj_heal_total"))
        skew_block, _ = _observatory_summary()
        pa = obs.events("plan_adapt")
        tier = pa[-1]["tier"] if pa else "shuffle"
        _restore("DJ_PLAN_ADAPT")
        _restore("DJ_BROADCAST_BYTES")
        return {
            "p50_s": _round(qs[50]),
            "p95_s": _round(qs[95]),
            "completed": completed,
            "wall_s": round(wall, 3),
            "heals": heals,
            "tier": tier,
            "errors": errors,
        }

    shuffle_arm = _arm(adaptive=False)
    adaptive_arm = _arm(adaptive=True)
    ratio = (
        round(adaptive_arm["p95_s"] / shuffle_arm["p95_s"], 4)
        if adaptive_arm["p95_s"] and shuffle_arm["p95_s"]
        else None
    )
    print(
        json.dumps(
            {
                "metric": "serve_skew_ab",
                "value": ratio,
                "unit": "adaptive/shuffle-only p95 s ratio "
                        "(<1 = adaptive planner wins; CPU trend only)",
                "rows": rows,
                "build_rows": build_rows,
                "queries": queries,
                "clients": CLIENTS,
                "hot_keys": hot_keys,
                "hot_fraction": hot_fraction,
                "plan_tier": adaptive_arm["tier"],
                "adaptive": adaptive_arm,
                "shuffle_only": shuffle_arm,
            }
        )
    )


def unique_shapes_ab():
    """Shape-churn A/B (the ``serve_shape_churn_ab`` BENCH_LOG entry):
    a closed-loop stream where EVERY query has a distinct row count —
    today's worst case for the per-exact-shape module cache — driven
    through the scheduler against one resident PreparedSide, bucketing
    OFF vs ON (DJ_SHAPE_BUCKET=1). Off, every shape compiles its own
    prepared-query module (~1 module per query, dj_compile_seconds
    dominating the tail); on, shapes collapse onto the geometric grid
    and the compiled-module count is bounded by the grid size. A third
    mini-arm (bucketing on, every query the SAME shape) gives the
    flat-p95 reference the acceptance bar compares against, and a
    direct off-vs-on join pins row-exactness (full-row multiset).
    value = bucketed/unbucketed p95 ratio on the unique-shape stream
    (< 1 = bucketing wins); the entry carries ``shape_bucket`` so
    bench_trend groups it apart from exact-shape medians."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    import dj_tpu.parallel.dist_join as DJ
    from dj_tpu.core import table as T
    from dj_tpu.parallel import shape_bucket as SB
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.serve import QueryScheduler, ServeConfig

    base = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 24_000))
    queries = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 10))
    step = int(os.environ.get("DJ_SERVE_BENCH_ROW_STEP", 256))
    build_rows = int(
        os.environ.get("DJ_SERVE_BENCH_BUILD_ROWS", 2 * base)
    )
    key_hi = 2 * build_rows

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    rk = rng.integers(0, key_hi, build_rows).astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(build_rows, dtype=np.int64))
    )
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, key_hi - 1),
    )
    # Every query a DISTINCT row count: the million-distinct-shapes
    # stream in miniature.
    row_counts = [base + i * step for i in range(queries)]
    lefts = []
    for rows_i in row_counts:
        pk = rng.integers(0, key_hi, rows_i).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(pk, np.arange(rows_i, dtype=np.int64))
            )
        )
    max_cap = lefts[-1][0].capacity

    # The query-module builder population the grid exists to bound.
    _QUERY_BUILDERS = (
        DJ._build_prepared_query_fn, DJ._build_coalesced_query_fn,
        DJ._build_join_fn, DJ._build_coalesced_join_fn,
    )

    def _modules():
        return sum(b.cache_info().misses for b in _QUERY_BUILDERS)

    def _compile_s():
        from dj_tpu.obs import metrics as M

        return sum(
            M.counter_value(
                "dj_compile_seconds_total", builder=b.__wrapped__.__name__
            )
            for b in _QUERY_BUILDERS
        )

    # The bench rewrites the bucketing knobs per arm; the operator's
    # ambient values must survive out of the process.
    ambient = {
        k: os.environ.get(k)
        for k in ("DJ_SHAPE_BUCKET", "DJ_SHAPE_BUCKET_RATIO",
                  "DJ_SHAPE_BUCKET_MIN")
    }

    def _restore():
        for k, v in ambient.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _arm(bucketed: bool, same_shape: bool = False):
        dj_ledger.reset()
        resil.reset_pins()
        obs.reset(reenable=True)
        obs.drain()
        if bucketed:
            os.environ["DJ_SHAPE_BUCKET"] = "1"
        else:
            os.environ.pop("DJ_SHAPE_BUCKET", None)
        arm_lefts = (
            [lefts[0]] * queries if same_shape else lefts
        )
        modules0 = _modules()
        prep = dj_tpu.prepare_join_side(
            topo, right, rc, [0], config, left_capacity=max_cap
        )
        # Coalescing OFF, the index_ab precedent: each distinct group
        # size compiles its own (large) fused module inline, and a
        # 10-query A/B would spend its window tracing coalesced
        # variants — serve_closed_loop already trends coalescing; this
        # entry isolates per-bucket module sharing, so bucketing-on's
        # module count is comparable against the grid size directly.
        sched = QueryScheduler(ServeConfig(coalesce=False))
        errors: dict[str, int] = {}
        errlock = threading.Lock()

        def _run_one(i):
            lt, lc = arm_lefts[i]
            try:
                t = sched.submit(
                    topo, lt, lc, prep, None, [0], None, config
                )
                t.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with errlock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1

        # Warm one query untimed (both arms pay their first trace
        # outside the window); the off arm's REMAINING distinct shapes
        # still compile inside it — that churn IS the measurement. The
        # BUCKETED arm additionally warms one query per grid bucket:
        # the deployable protocol bucketing exists to enable (a grid
        # is finite and warmable at deploy, the bucketed analogue of
        # warmup_prepared_join; a million distinct raw shapes are
        # not), so its timed window measures steady-state serving.
        _run_one(0)
        if bucketed and not same_shape:
            w = topo.world_size
            seen = set()
            for i, (lt, _) in enumerate(arm_lefts):
                b = SB.bucket_capacity(lt.capacity // w)
                if b not in seen:
                    seen.add(b)
                    _run_one(i)
        obs.reset(reenable=True)
        t0 = time.perf_counter()
        nclients = max(1, CLIENTS)
        b, rem = divmod(queries, nclients)
        starts = [c * b + min(c, rem) for c in range(nclients + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(nclients)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        qs, completed = _hist_latency()
        out = {
            "p50_s": _round(qs[50]),
            "p95_s": _round(qs[95]),
            "completed": completed,
            "wall_s": round(wall, 3),
            "modules": _modules() - modules0,
            "compile_s": round(_compile_s(), 3),
            "coalesced": int(
                obs.counter_value("dj_serve_coalesced_total")
            ),
            "errors": errors,
        }
        _restore()
        return out

    off = _arm(bucketed=False)
    on = _arm(bucketed=True)
    same = _arm(bucketed=True, same_shape=True)

    # Row-exactness: the largest raw shape joined directly, bucketing
    # off vs on — identical valid-row multisets.
    def _join_rows(bucketed: bool):
        if bucketed:
            os.environ["DJ_SHAPE_BUCKET"] = "1"
        else:
            os.environ.pop("DJ_SHAPE_BUCKET", None)
        lt, lc = lefts[-1]
        out, counts, _, _ = dj_tpu.distributed_inner_join_auto(
            topo, lt, lc, right, rc, [0], [0], config,
        )
        host = dj_tpu.unshard_table(out, counts)
        rows = np.stack([np.asarray(c.data) for c in host.columns])
        _restore()
        return rows[:, np.lexsort(rows)]

    row_exact = bool(np.array_equal(_join_rows(False), _join_rows(True)))

    os.environ["DJ_SHAPE_BUCKET"] = "1"
    w = topo.world_size
    grid_buckets = SB.grid_points(
        lefts[0][0].capacity // w, max_cap // w
    )
    _restore()
    ratio = (
        round(on["p95_s"] / off["p95_s"], 4)
        if on["p95_s"] and off["p95_s"]
        else None
    )
    print(
        json.dumps(
            {
                "metric": "serve_shape_churn_ab",
                "value": ratio,
                "unit": "bucketed/unbucketed p95 s ratio on a "
                        "per-query-unique-shape stream (<1 = bucketing "
                        "wins; CPU trend only)",
                "shape_bucket": True,
                "rows": base,
                "row_step": step,
                "build_rows": build_rows,
                "queries": queries,
                "clients": CLIENTS,
                "grid_buckets": grid_buckets,
                "row_exact": row_exact,
                "p95_same_shape_s": same["p95_s"],
                "on": on,
                "off": off,
                "same_shape": same,
            }
        )
    )


def autotune_ab():
    """Per-signature autotuner on vs hand-tuned defaults (the
    ``serve_autotune_ab`` BENCH_LOG entry; module docstring has the
    design). Two prepared streams — same-shape (one plan signature)
    and mixed (two signatures alternating) — each served twice through
    the scheduler with identical workloads and fresh
    ledger/pins/registry/tuner state per arm. The acceptance bars ride
    the entry: same-shape tuned p95 within 1.05x of hand-tuned (the
    tune itself is paid in the untimed per-signature warm — the deploy
    protocol), mixed-stream tuned p95 under 0.8x (the tuner's
    probe-merge pick vs the wrong-by-default xla merge), row-exact,
    and warm-window tune count == distinct signatures with ZERO tunes
    inside any timed window."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.parallel import autotune
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.serve import QueryScheduler, ServeConfig

    rows = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 100_000))
    queries = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 16))
    # The steady-state serving shape (the cpu_mesh probe-AB precedent):
    # SMALL query batches against a full-size resident side. The probe
    # tier's economics — 2*log2(R) gathers of bl rows vs a
    # (bl+br)-sized sort — only win there; at symmetric batch sizes
    # the sort's cache-friendly passes win and the tuner (correctly)
    # keeps the default.
    q_rows = max(8, rows // 32)

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    key_hi = 2 * rows
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, key_hi - 1),
    )
    # Two prepared SIGNATURES (distinct build payload schemas — plan
    # signatures are schema-level): the mixed stream alternates them,
    # the same-shape stream serves only the first.
    rk_a = rng.integers(0, key_hi, rows).astype(np.int64)
    right_a, rca = dj_tpu.shard_table(
        topo, T.from_arrays(rk_a, np.arange(rows, dtype=np.int64))
    )
    rk_b = rng.integers(0, key_hi, rows).astype(np.int64)
    right_b, rcb = dj_tpu.shard_table(
        topo, T.from_arrays(rk_b, np.arange(rows, dtype=np.int64),
                            np.arange(rows, dtype=np.int64)),
    )
    prep_a = dj_tpu.prepare_join_side(
        topo, right_a, rca, [0], config, left_capacity=q_rows
    )
    prep_b = dj_tpu.prepare_join_side(
        topo, right_b, rcb, [0], config, left_capacity=q_rows
    )
    lefts = []
    for q in range(DISTINCT_LEFTS):
        pk = rng.integers(0, key_hi, q_rows).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo,
                T.from_arrays(pk, np.arange(q_rows, dtype=np.int64)),
            )
        )

    # Both arms start from the MERGE DEFAULT (xla) — the hand-tuned
    # baseline the tuner is judged against — whatever the operator's
    # ambient knobs say; restored on the way out. The pallas merge
    # candidate is dropped from the default candidate set (a hardware
    # merge tier; the infeasible-candidate path is unit-tested) — an
    # operator's explicit DJ_AUTOTUNE_MERGE wins.
    ambient = {
        k: os.environ.get(k)
        for k in ("DJ_AUTOTUNE", "DJ_JOIN_MERGE", "DJ_AUTOTUNE_MERGE")
    }

    def _restore():
        for k, v in ambient.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    os.environ.setdefault("DJ_AUTOTUNE_MERGE", "xla,probe")

    streams = {
        "same_shape": [prep_a] * queries,
        "mixed": [prep_a if i % 2 == 0 else prep_b
                  for i in range(queries)],
    }

    def _arm(stream, tuned: bool):
        # Fresh serving state per arm: learned factors, tuned
        # decisions, tier pins, and the latency histogram must not
        # leak across arms (obs.reset also clears the tuner's
        # in-memory state via its registered aux reset).
        dj_ledger.reset()
        resil.reset_pins()
        obs.reset(reenable=True)
        obs.drain()
        os.environ.pop("DJ_JOIN_MERGE", None)
        if tuned:
            os.environ["DJ_AUTOTUNE"] = "1"
        else:
            os.environ.pop("DJ_AUTOTUNE", None)
        # Coalescing OFF in BOTH arms (the index_ab precedent, and the
        # armed tuner disables it anyway): the A/B isolates plan-knob
        # selection, not group batching.
        sched = QueryScheduler(ServeConfig(coalesce=False))
        errors: dict[str, int] = {}
        errlock = threading.Lock()

        def _run_one(i):
            lt, lc = lefts[i % DISTINCT_LEFTS]
            try:
                t = sched.submit(
                    topo, lt, lc, stream[i], None, [0], None, config
                )
                t.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with errlock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1

        # Deploy protocol (the shape-churn precedent): ONE warm query
        # per distinct signature, untimed — the tuned arm's tune
        # (candidate pricing + top-2 probe dispatches) happens exactly
        # here, so the timed window compares steady-state serving.
        t0 = time.perf_counter()
        seen: set = set()
        for i, prep in enumerate(stream):
            if id(prep) not in seen:
                seen.add(id(prep))
                _run_one(i)
        warm_s = time.perf_counter() - t0
        tunes_warm = int(
            obs.counter_value("dj_autotune_total", action="tune")
        )
        # reset clears counters (and the tuner's in-memory state via
        # its aux hook — the in-window dispatches must REPLAY from the
        # ledger); drain clears the event ring so the warm queries'
        # serve events never join the timed-window samples.
        obs.reset(reenable=True)
        obs.drain()
        t0 = time.perf_counter()
        nclients = max(1, CLIENTS)
        b, rem = divmod(len(stream), nclients)
        starts = [c * b + min(c, rem) for c in range(nclients + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(nclients)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        # EXACT per-query latencies from the serve events, not the
        # bucket-resolution histogram quantiles: an A/B between arms a
        # small constant factor apart collapses to ratio 1.0 when both
        # p95s quantize onto the same log-spaced bucket edge.
        samples = sorted(
            float(e["total_s"]) for e in obs.events("serve")
            if e.get("outcome") == "result"
        )
        completed = len(samples)

        def _pct(p):
            if not samples:
                return None
            return samples[int(p * (len(samples) - 1))]
        # The in-window tuner traffic must be REPLAYS only (the warm
        # query tuned; obs.reset cleared the in-memory decision, so
        # the first in-window dispatch per signature replays from the
        # ledger with zero probes) — a nonzero in-window tune count
        # means the decide-once contract broke.
        tunes_window = int(
            obs.counter_value("dj_autotune_total", action="tune")
        )
        replays = int(
            obs.counter_value("dj_autotune_total", action="replay")
        )
        tuned_serves = sum(
            1 for e in obs.events("serve") if e.get("autotuned")
        )
        decisions = {}
        if tuned:
            for sig, d in autotune.tunez_summary()["signatures"].items():
                decisions[sig[:120]] = {
                    k: d.get(k)
                    for k in ("odf", "merge", "bucket_ratio", "source")
                }
        out = {
            "p50_s": _round(_pct(0.50)),
            "p95_s": _round(_pct(0.95)),
            "completed": completed,
            "wall_s": round(wall, 3),
            "warm_s": round(warm_s, 3),
            "tunes_warm": tunes_warm,
            "tunes_in_window": tunes_window,
            "replays_in_window": replays,
            "tuned_serves": tuned_serves,
            "errors": errors,
        }
        if tuned:
            out["decisions"] = decisions
        _restore()
        os.environ.setdefault("DJ_AUTOTUNE_MERGE", "xla,probe")
        return out

    arms = {}
    for name, stream in streams.items():
        arms[name] = {
            "hand_tuned": _arm(stream, tuned=False),
            "autotuned": _arm(stream, tuned=True),
        }

    # Row-exactness: one representative query joined directly under
    # the hand-tuned default vs under the tuned arm's winning merge
    # tier — identical valid-row multisets (the tier-equality contract
    # the merge A/Bs already pin; the entry re-verifies on THIS
    # workload).
    tuned_merges = sorted(
        {
            d.get("merge")
            for arm in arms.values()
            for d in arm["autotuned"].get("decisions", {}).values()
            if d.get("merge") is not None
        }
    )

    def _join_rows(merge):
        if merge is None:
            os.environ.pop("DJ_JOIN_MERGE", None)
        else:
            os.environ["DJ_JOIN_MERGE"] = str(merge)
        lt, lc = lefts[0]
        out, counts, _ = dj_tpu.distributed_inner_join(
            topo, lt, lc, prep_a, None, [0], None, config
        )
        host = dj_tpu.unshard_table(out, counts)
        mat = np.stack([np.asarray(c.data) for c in host.columns])
        os.environ.pop("DJ_JOIN_MERGE", None)
        _restore()
        return mat[:, np.lexsort(mat)]

    row_exact = all(
        bool(np.array_equal(_join_rows(None), _join_rows(m)))
        for m in tuned_merges
    )

    distinct_sigs = {"same_shape": 1, "mixed": 2}
    tune_count_ok = all(
        arms[n]["autotuned"]["tunes_warm"] == distinct_sigs[n]
        and arms[n]["autotuned"]["tunes_in_window"] == 0
        and arms[n]["hand_tuned"]["tunes_warm"] == 0
        for n in arms
    )

    def _ratio(name):
        a = arms[name]["autotuned"]["p95_s"]
        h = arms[name]["hand_tuned"]["p95_s"]
        return round(a / h, 4) if a and h else None

    ratio_same = _ratio("same_shape")
    ratio_mixed = _ratio("mixed")
    _restore()
    print(
        json.dumps(
            {
                "metric": "serve_autotune_ab",
                "value": ratio_mixed,
                "unit": "autotuned/hand-tuned p95 s ratio on the "
                        "mixed two-signature stream (<1 = the tuner "
                        "wins; CPU trend only)",
                "autotuned": True,
                "rows": rows,
                "q_rows": q_rows,
                "queries": queries,
                "clients": CLIENTS,
                "ratio_mixed": ratio_mixed,
                "ratio_same_shape": ratio_same,
                "meets_same_shape_bar": (
                    ratio_same is not None and ratio_same <= 1.05
                ),
                "meets_mixed_bar": (
                    ratio_mixed is not None and ratio_mixed < 0.8
                ),
                "row_exact": row_exact,
                "tune_count_ok": tune_count_ok,
                "tuned_merges": tuned_merges,
                "arms": arms,
            }
        )
    )


def prepared_tier_ab():
    """Prepared-tier A/B at the steady-state serving shape (the
    ``serve_prepared_tier_ab`` BENCH_LOG entry; PR 17). One build
    table, three arms — shuffle-prepared (the PR-6 baseline: every
    query pays a left all-to-all shuffle), probe (shuffle-prepared
    under the DJ_JOIN_MERGE=probe merge, the PR-13 hot path — still
    shuffles), and broadcast-prepared (DJ_PREPARED_TIER=broadcast:
    the sorted runs were replicated at prepare time, so the per-query
    module traces ZERO collectives; tests/test_prepared_tier.py pins
    the HLO claim, this entry measures what it buys) — each driven
    closed-loop through the scheduler with fresh ledger/pins/obs
    state and its OWN prepared side built under the forced tier.
    Deploy protocol: one untimed warm query per arm (each arm has one
    plan signature), then the timed window with event-exact
    percentiles. The acceptance bar rides the entry:
    broadcast-prepared p95 <= 0.8x shuffle-prepared at the serving
    shape (q_rows = rows/32 against a full-size resident side — the
    regime where the left shuffle IS the query cost), and every arm
    row-exact vs a fresh UNPREPARED join of the same tables."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.serve import QueryScheduler, ServeConfig

    rows = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 100_000))
    queries = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 16))
    # The serving shape (the probe-merge and autotune A/B precedent):
    # SMALL query batches against a full-size resident side. At
    # symmetric sizes the per-query left shuffle is a small fraction
    # of the merge cost and no tier separates; at rows/32 the shuffle
    # (launch overhead + all-to-all) dominates, which is exactly the
    # regime the broadcast tier exists for.
    q_rows = max(8, rows // 32)

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    key_hi = 2 * rows
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, key_hi - 1),
    )
    rk = rng.integers(0, key_hi, rows).astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(rows, dtype=np.int64))
    )
    lefts = []
    for q in range(DISTINCT_LEFTS):
        pk = rng.integers(0, key_hi, q_rows).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo,
                T.from_arrays(pk, np.arange(q_rows, dtype=np.int64)),
            )
        )

    ambient = {
        k: os.environ.get(k)
        for k in ("DJ_PREPARED_TIER", "DJ_JOIN_MERGE", "DJ_AUTOTUNE")
    }

    def _restore():
        for k, v in ambient.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # The A/B isolates the PREPARED BUILD TIER: the tuner stays off
    # (it would converge every arm onto its own winner) and the tier
    # is forced per arm via prepare_join_side(tier=...), not ambient
    # env — the side object carries the decision to the dispatch.
    os.environ.pop("DJ_AUTOTUNE", None)
    os.environ.pop("DJ_PREPARED_TIER", None)

    preps = {}

    def _arm(name, tier, merge):
        # Fresh serving state per arm: learned factors, tier pins,
        # ledger tier records, and the latency histogram must not
        # leak across arms.
        dj_ledger.reset()
        resil.reset_pins()
        obs.reset(reenable=True)
        obs.drain()
        if merge is None:
            os.environ.pop("DJ_JOIN_MERGE", None)
        else:
            os.environ["DJ_JOIN_MERGE"] = str(merge)
        t0 = time.perf_counter()
        prep = dj_tpu.prepare_join_side(
            topo, right, rc, [0], config,
            left_capacity=q_rows, tier=tier,
        )
        prepare_s = time.perf_counter() - t0
        preps[name] = (prep, merge)
        # Coalescing OFF (the autotune_ab precedent): the A/B
        # isolates the per-query module, not group batching.
        sched = QueryScheduler(ServeConfig(coalesce=False))
        errors: dict[str, int] = {}
        errlock = threading.Lock()

        def _run_one(i):
            lt, lc = lefts[i % DISTINCT_LEFTS]
            try:
                t = sched.submit(
                    topo, lt, lc, prep, None, [0], None, config
                )
                t.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with errlock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1

        # Deploy protocol: ONE untimed warm query (one signature per
        # arm) pays the trace; the timed window is steady state.
        t0 = time.perf_counter()
        _run_one(0)
        warm_s = time.perf_counter() - t0
        obs.reset(reenable=True)
        obs.drain()
        t0 = time.perf_counter()
        nclients = max(1, CLIENTS)
        b, rem = divmod(queries, nclients)
        starts = [c * b + min(c, rem) for c in range(nclients + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(nclients)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        # EXACT per-query latencies from the serve events (the
        # autotune_ab precedent): arms a small constant factor apart
        # collapse to ratio 1.0 on log-spaced histogram bucket edges.
        samples = sorted(
            float(e["total_s"]) for e in obs.events("serve")
            if e.get("outcome") == "result"
        )

        def _pct(p):
            if not samples:
                return None
            return samples[int(p * (len(samples) - 1))]

        os.environ.pop("DJ_JOIN_MERGE", None)
        return {
            # the tier the side actually CARRIES (a forced-tier
            # misfit demotes at prepare; the entry must say what ran)
            "prepared_tier": prep.tier,
            "merge": merge or "xla",
            "p50_s": _round(_pct(0.50)),
            "p95_s": _round(_pct(0.95)),
            "completed": len(samples),
            "wall_s": round(wall, 3),
            "warm_s": round(warm_s, 3),
            "prepare_s": round(prepare_s, 3),
            "errors": errors,
        }

    # The broadcast arm runs the ENDGAME config — broadcast-prepared
    # side + probe merge (rank_in_run binary search into the resident
    # replicated run: no per-query sort, no collectives). The xla
    # concat-sort would re-sort the full replicated run (n*r_cap
    # rows) every query and lose on merge cost what it saved on the
    # shuffle; the probe merge's log2(R) gathers barely notice the
    # replication, which is why the tiers compose. The probe arm
    # (shuffle-prepared + probe merge) sits between them so the entry
    # separates the merge win from the zero-collective win.
    arms = {
        "shuffle": _arm("shuffle", "shuffle", None),
        "probe": _arm("probe", "shuffle", "probe"),
        "broadcast": _arm("broadcast", "broadcast", "probe"),
    }

    # Row-exactness: one representative query through each arm's
    # prepared side vs a fresh UNPREPARED join of the same tables —
    # identical valid-row multisets (the replicated/salted runs and
    # the zero-collective module must change nothing about WHICH rows
    # come back).
    lt, lc = lefts[0]

    def _sorted_rows(out, counts):
        host = dj_tpu.unshard_table(out, counts)
        mat = np.stack([np.asarray(c.data) for c in host.columns])
        return mat[:, np.lexsort(mat)]

    out, counts, _, _ = dj_tpu.distributed_inner_join_auto(
        topo, lt, lc, right, rc, [0], [0], config
    )
    oracle = _sorted_rows(out, counts)

    def _prep_rows(name):
        prep, merge = preps[name]
        if merge is None:
            os.environ.pop("DJ_JOIN_MERGE", None)
        else:
            os.environ["DJ_JOIN_MERGE"] = str(merge)
        out, counts, _ = dj_tpu.distributed_inner_join(
            topo, lt, lc, prep, None, [0], None, config
        )
        os.environ.pop("DJ_JOIN_MERGE", None)
        return _sorted_rows(out, counts)

    row_exact = all(
        bool(np.array_equal(oracle, _prep_rows(n))) for n in arms
    )
    tiers_ok = (
        arms["broadcast"]["prepared_tier"] == "broadcast"
        and arms["shuffle"]["prepared_tier"] == "shuffle"
    )
    _restore()

    def _ratio(name):
        a = arms[name]["p95_s"]
        s = arms["shuffle"]["p95_s"]
        return round(a / s, 4) if a and s else None

    ratio_broadcast = _ratio("broadcast")
    ratio_probe = _ratio("probe")
    print(
        json.dumps(
            {
                "metric": "serve_prepared_tier_ab",
                "value": ratio_broadcast,
                "unit": "broadcast-/shuffle-prepared p95 s ratio at "
                        "the q_rows=rows/32 serving shape (<1 = the "
                        "zero-collective tier wins; CPU trend only)",
                "prepared_tier": "ab",
                "rows": rows,
                "q_rows": q_rows,
                "queries": queries,
                "clients": CLIENTS,
                "ratio_broadcast": ratio_broadcast,
                "ratio_probe": ratio_probe,
                "meets_broadcast_bar": (
                    ratio_broadcast is not None
                    and ratio_broadcast <= 0.8
                ),
                "row_exact": row_exact,
                "tiers_ok": tiers_ok,
                "arms": arms,
            }
        )
    )


def pipeline_ab():
    """Multi-join pipeline A/B at the Q3 shape (the
    ``serve_pipeline_ab`` BENCH_LOG entry; PR 18). One workload —
    lineitem (fresh per query) ⋈ orders ⋈ customer — served through
    the scheduler two ways with fresh ledger/obs state per arm:

    - pipeline: ONE ``submit_pipeline`` query per probe. The
      intermediate stays device-resident and sharded, its key range
      derives statically from the input plans (zero host probes), and
      the customer dim stage routes through the broadcast tier (zero
      all-to-alls; tests/test_pipeline.py pins both HLO claims — this
      entry measures what they buy).
    - composed: TWO back-to-back ``submit`` queries per probe, the
      pre-PR-18 shape. The stage-0 result comes home as a query
      payload, then re-enters admission as the stage-1 left: a fresh
      buffer, so it pays new key-range probes (2 host syncs) and a
      full hash-partition + all-to-all of the (large) intermediate.

    Per-query latency is driver-side submit→final-result wall time:
    a composed query is TWO serve events, so the per-event histogram
    cannot express its end-to-end cost; identical timing on both arms
    keeps the ratio honest. Deploy protocol: one untimed warm query
    per arm, then the timed window with exact percentiles. The
    acceptance bar rides the entry: pipeline p95 < 0.8x composed, and
    the pipeline output row-exact vs the composed output."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.serve import QueryScheduler, ServeConfig

    rows = int(os.environ.get("DJ_SERVE_BENCH_ROWS", 100_000))
    queries = int(os.environ.get("DJ_SERVE_BENCH_QUERIES", 16))
    # TPC-H-ish cardinality ladder: ~4 lineitems per order, ~10 orders
    # per customer. Unique order/customer keys -> every lineitem joins
    # exactly one order and one customer, so each stage's output rows
    # == its input rows (no fan-out; factor-2 capacity headroom keeps
    # hash-partition skew from triggering a mid-window heal).
    n_orders = max(64, rows // 4)
    n_cust = max(8, rows // 32)

    obs.enable()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    config = dj_tpu.JoinConfig(
        over_decom_factor=1, bucket_factor=2.0, join_out_factor=2.0,
    )
    ok = np.arange(n_orders, dtype=np.int64)
    rng.shuffle(ok)
    orders, oc = dj_tpu.shard_table(
        topo,
        T.from_arrays(
            ok,
            rng.integers(0, n_cust, n_orders).astype(np.int64),  # custkey
            np.arange(n_orders, dtype=np.int64),
        ),
    )
    ck = np.arange(n_cust, dtype=np.int64)
    rng.shuffle(ck)
    customer, cc = dj_tpu.shard_table(
        topo, T.from_arrays(ck, np.arange(n_cust, dtype=np.int64))
    )
    lefts = []
    for q in range(DISTINCT_LEFTS):
        lk = rng.integers(0, n_orders, rows).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(lk, np.arange(rows, dtype=np.int64))
            )
        )
    # O_CUSTKEY's position in the stage-0 intermediate: 2 lineitem
    # columns + custkey first among the orders payload columns.
    custkey = 2
    stages = [
        dj_tpu.JoinStage(
            right=orders, right_counts=oc, left_on=(0,), right_on=(0,)
        ),
        dj_tpu.JoinStage(
            right=customer, right_counts=cc,
            left_on=(custkey,), right_on=(0,),
        ),
    ]

    def _arm(pipelined: bool):
        # Fresh serving state per arm (the prepared_tier_ab
        # precedent): learned factors, pins, and events must not leak.
        dj_ledger.reset()
        resil.reset_pins()
        obs.reset(reenable=True)
        obs.drain()
        # Coalescing OFF: the A/B isolates the per-query chain.
        sched = QueryScheduler(ServeConfig(coalesce=False))
        errors: dict[str, int] = {}
        samples: list[float] = []
        lock = threading.Lock()

        def _run_one(i, timed=True):
            lt, lc = lefts[i % DISTINCT_LEFTS]
            t0 = time.perf_counter()
            try:
                if pipelined:
                    t = sched.submit_pipeline(topo, lt, lc, stages, config)
                    t.result(timeout=600)
                else:
                    t1 = sched.submit(
                        topo, lt, lc, orders, oc, [0], [0], config
                    )
                    r1 = t1.result(timeout=600)
                    t2 = sched.submit(
                        topo, r1[0], r1[1], customer, cc,
                        [custkey], [0], config,
                    )
                    t2.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with lock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1
                return
            if timed:
                with lock:
                    samples.append(time.perf_counter() - t0)

        # Deploy protocol: one untimed warm query pays the traces.
        t0 = time.perf_counter()
        _run_one(0, timed=False)
        warm_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        nclients = max(1, CLIENTS)
        b, rem = divmod(queries, nclients)
        starts = [c * b + min(c, rem) for c in range(nclients + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(nclients)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        samples.sort()

        def _pct(p):
            if not samples:
                return None
            return samples[int(p * (len(samples) - 1))]

        return {
            "p50_s": _round(_pct(0.50)),
            "p95_s": _round(_pct(0.95)),
            "completed": len(samples),
            "wall_s": round(wall, 3),
            "warm_s": round(warm_s, 3),
            "errors": errors,
        }

    arms = {
        "composed": _arm(False),
        "pipeline": _arm(True),
    }

    # Row-exactness: one representative probe through both paths —
    # identical valid-row multisets (the device-resident intermediate,
    # derived ranges, and elided collectives must change nothing about
    # WHICH rows come back).
    lt, lc = lefts[0]

    def _sorted_rows(out, counts):
        host = dj_tpu.unshard_table(out, counts)
        mat = np.stack([np.asarray(c.data) for c in host.columns])
        return mat[:, np.lexsort(mat)]

    out1, c1, _, _ = dj_tpu.distributed_inner_join_auto(
        topo, lt, lc, orders, oc, [0], [0], config
    )
    out2, c2, _, _ = dj_tpu.distributed_inner_join_auto(
        topo, out1, c1, customer, cc, [custkey], [0], config
    )
    pout, pc, _, _ = dj_tpu.distributed_join_pipeline_auto(
        topo, lt, lc, stages, config
    )
    row_exact = bool(
        np.array_equal(_sorted_rows(out2, c2), _sorted_rows(pout, pc))
    )

    a = arms["pipeline"]["p95_s"]
    s = arms["composed"]["p95_s"]
    ratio = round(a / s, 4) if a and s else None
    print(
        json.dumps(
            {
                "metric": "serve_pipeline_ab",
                "value": ratio,
                "unit": "pipeline/composed per-query p95 s ratio at "
                        "the Q3 shape (<1 = one device-resident chain "
                        "beats back-to-back joins; CPU trend only)",
                "pipeline": "ab",
                "rows": rows,
                "n_orders": n_orders,
                "n_customers": n_cust,
                "queries": queries,
                "clients": CLIENTS,
                "ratio_pipeline": ratio,
                "meets_pipeline_bar": ratio is not None and ratio < 0.8,
                "row_exact": row_exact,
                "arms": arms,
            }
        )
    )


def obs_ab():
    """--obs-ab: the full-observatory overhead A/B (module docstring).
    One prepared single-join closed loop served twice through per-arm
    schedulers: obs fully OFF vs the FULL observatory (obs +
    DJ_OBS_SKEW + DJ_HLO_AUDIT + the crash black-box armed into a
    temp dir). Latency is driver-side submit->result wall time — the
    off arm has no histogram by construction — and the shared prepared
    side + warm compile keep both arms on identical compiled modules
    (the repo's standing HLO-equality guarantee, here exercised at
    full armament)."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import tempfile

    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.obs import forensics as obs_forensics
    from dj_tpu.core import table as T
    from dj_tpu.serve import QueryScheduler, ServeConfig

    rows, queries = ROWS, QUERIES
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    build = rng.integers(0, 2 * rows, rows).astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(build, np.arange(rows, dtype=np.int64))
    )
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, 2 * rows - 1),
    )
    prep = dj_tpu.prepare_join_side(
        topo, right, rc, [0], config, left_capacity=rows
    )
    lefts = []
    for q in range(DISTINCT_LEFTS):
        probe = rng.integers(0, 2 * rows, rows).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(probe, np.arange(rows, dtype=np.int64))
            )
        )
    # Shared compile warm OUTSIDE both arms: the A/B measures serving
    # overhead, not whether telemetry changes compile time (it cannot —
    # the hlo_count byte-equality guard proves the modules identical).
    dj_tpu.warmup_prepared_join(
        topo, prep, lefts[0][0], lefts[0][1], [0], config
    )

    # The knobs the ON arm arms; both arms save/restore so an
    # inherited environment can't tilt either side.
    armed_env = ("DJ_OBS_SKEW", "DJ_HLO_AUDIT")

    def _arm(observed: bool):
        saved = {k: os.environ.pop(k, None) for k in armed_env}
        bb_dir = None
        if observed:
            os.environ["DJ_OBS_SKEW"] = "1"
            os.environ["DJ_HLO_AUDIT"] = "1"
            obs.reset(reenable=True)
            obs.drain()
            bb_dir = tempfile.mkdtemp(prefix="dj-obs-ab-blackbox-")
            obs_forensics.arm(bb_dir)
        else:
            # Fully dark: registry off, ring drained, no probes.
            obs.reset(reenable=False)
            obs.drain()
        sched = QueryScheduler(ServeConfig(coalesce=False))
        errors: dict[str, int] = {}
        samples: list[float] = []
        lock = threading.Lock()

        def _run_one(i, timed=True):
            lt, lc = lefts[i % DISTINCT_LEFTS]
            t0 = time.perf_counter()
            try:
                t = sched.submit(
                    topo, lt, lc, prep, None, [0], None, config
                )
                t.result(timeout=600)
            except Exception as e:  # noqa: BLE001 - bench counts
                with lock:
                    k = type(e).__name__
                    errors[k] = errors.get(k, 0) + 1
                return
            if timed:
                with lock:
                    samples.append(time.perf_counter() - t0)

        # Deploy protocol: one untimed warm query per arm (the ON
        # arm's audit + skew probe first-hits land exactly there).
        _run_one(0, timed=False)
        t0 = time.perf_counter()
        nclients = max(1, CLIENTS)
        b, rem = divmod(queries, nclients)
        starts = [c * b + min(c, rem) for c in range(nclients + 1)]
        threads = [
            threading.Thread(
                target=lambda c=c: [
                    _run_one(i) for i in range(starts[c], starts[c + 1])
                ],
                daemon=True,
            )
            for c in range(nclients)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.perf_counter() - t0
        sched.close()
        bundle = None
        if observed:
            # A clean dump proves the bundle machinery works on THIS
            # process before disarming (the bench doubles as an
            # end-to-end forensics check).
            bundle = obs_forensics.dump("obs_ab")
            obs_forensics.disarm()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        samples.sort()

        def _pct(p):
            if not samples:
                return None
            return samples[int(p * (len(samples) - 1))]

        return {
            "p50_s": _round(_pct(0.50)),
            "p95_s": _round(_pct(0.95)),
            "completed": len(samples),
            "wall_s": round(wall, 3),
            "errors": errors,
            "blackbox_bundle": bundle,
        }

    arms = {
        "obs_off": _arm(False),
        "obs_full": _arm(True),
    }
    # Leave obs enabled for the post-run _write_metrics hook.
    obs.enable()
    a = arms["obs_full"]["p95_s"]
    s = arms["obs_off"]["p95_s"]
    ratio = round(a / s, 4) if a and s else None
    print(
        json.dumps(
            {
                "metric": "serve_obs_overhead_ab",
                "value": ratio,
                "unit": "full-observatory/obs-off per-query p95 s "
                        "ratio (<1.05 = telemetry stays off the query "
                        "path; CPU trend only)",
                "obs_ab": "ab",
                "rows": rows,
                "queries": queries,
                "clients": CLIENTS,
                "ratio_obs": ratio,
                "meets_obs_bar": ratio is not None and ratio < 1.05,
                "arms": arms,
            }
        )
    )


def multi_tenant():
    """--tenants N --tables M: the fleet-shaped closed loop — N client
    tenants round-robin over M distinct build tables, every submit a
    Table right THROUGH the JoinIndexCache-backed scheduler. The first
    query per (tenant, table) pays the prepare; steady state is index
    hits + coalesced prepared queries."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    _arm_truth()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    config, builds, lefts = _mt_workload(dj_tpu, T, topo, rng)
    cache = dj_tpu.JoinIndexCache()
    sched = QueryScheduler(ServeConfig.from_env(), index=cache)
    errors: dict[str, int] = {}
    errlock = threading.Lock()

    def _run_one(i):
        lt, lc = lefts[i % len(lefts)]
        bt, bc = builds[i % TABLES]
        try:
            t = sched.submit(
                topo, lt, lc, bt, bc, [0], [0], config,
                tenant=f"tenant{i % TENANTS}",
            )
            t.result(timeout=600)
        except Exception as e:  # noqa: BLE001 - bench counts, never dies
            with errlock:
                k = type(e).__name__
                errors[k] = errors.get(k, 0) + 1

    base, rem = divmod(QUERIES, max(1, CLIENTS))
    starts = [c * base + min(c, rem) for c in range(max(1, CLIENTS) + 1)]

    def _client(c):
        for i in range(starts[c], starts[c + 1]):
            _run_one(i)

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=_client, args=(c,), daemon=True)
        for c in range(max(1, CLIENTS))
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=600)
    wall = time.perf_counter() - t0
    sched.close()
    qs, completed = _hist_latency()
    skew_block, roofline_block = _observatory_summary()
    print(
        json.dumps(
            {
                "metric": "serve_multi_tenant_8dev",
                "value": _round(qs[95]) if qs[95] is not None else -1.0,
                "unit": "p95 s/query (CPU trend only, not TPU perf)",
                "rows": ROWS,
                "queries": QUERIES,
                "clients": CLIENTS,
                "tenants": TENANTS,
                "tables": TABLES,
                "qps_submitted": round(QUERIES / wall, 3),
                "completed": completed,
                "latency_source": "dj_serve_latency_seconds histogram",
                "slo": _slo_summary(sched),
                "p50_s": _round(qs[50]),
                "p95_s": _round(qs[95]),
                "index_hits": int(obs.counter_value("dj_index_hit_total")),
                "index_misses": int(
                    obs.counter_value("dj_index_miss_total")
                ),
                "index_resident_mb": round(cache.resident_bytes / 1e6, 3),
                "skew": skew_block,
                "roofline": roofline_block,
                "truth": _truth_block(),
                "truth_armed": _truth_armed(),
                "errors": errors,
            }
        )
    )
    cache.clear(force=True)


def main():
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8; "
        f"got {jax.devices()}"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    _arm_truth()
    rng = np.random.default_rng(0)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    build = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(build, np.arange(ROWS, dtype=np.int64))
    )
    # key_range declared over the full generator range: the prepared
    # anchors cover every probe table, so no query pays a
    # plan-mismatch re-prepare mid-bench (without it, probe keys above
    # the BUILD side's observed max demote every coalesced member to
    # the singleton re-prepare path — the first logged run showed
    # exactly that in its embedded build-cache counters).
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=2.0, join_out_factor=1.0,
        key_range=(0, 2 * ROWS - 1),
    )
    prep = dj_tpu.prepare_join_side(
        topo, right, rc, [0], config, left_capacity=ROWS
    )
    # Distinct left tables (distinct tenants, one plan signature) so
    # coalescing has real work to batch and nothing degenerates to a
    # repeated-buffer cache artifact.
    lefts = []
    for q in range(DISTINCT_LEFTS):
        probe = rng.integers(0, 2 * ROWS, ROWS).astype(np.int64)
        lefts.append(
            dj_tpu.shard_table(
                topo, T.from_arrays(probe, np.arange(ROWS, dtype=np.int64))
            )
        )
    # Pre-pay the singleton compile so percentiles measure serving, not
    # one cold trace (the coalesced group sizes still compile inline —
    # that tail is part of what the bench reports). The warmup runs
    # under a forecast scope so the singleton query module — which the
    # loop will only ever cache-hit — still reconciles the workload's
    # admission forecast into dj_model_xla_ratio (the acceptance bar:
    # a populated histogram even when coalescing happens to never
    # group).
    from dj_tpu.obs import truth as obs_truth
    from dj_tpu.serve import forecast as serve_forecast

    fc = serve_forecast(topo, lefts[0][0], prep, [0], None, config)
    with obs_truth.forecast_scope(fc.bytes):
        dj_tpu.warmup_prepared_join(
            topo, prep, lefts[0][0], lefts[0][1], [0], config
        )
    obs.drain()

    sched = QueryScheduler(ServeConfig.from_env())
    errors: dict[str, int] = {}
    errlock = threading.Lock()

    def _run_one(i):
        lt, lc = lefts[i % DISTINCT_LEFTS]
        try:
            t = sched.submit(topo, lt, lc, prep, None, [0], None, config)
            t.result(timeout=600)
        except Exception as e:  # noqa: BLE001 - bench counts, never dies
            with errlock:
                k = type(e).__name__
                errors[k] = errors.get(k, 0) + 1

    t0 = time.perf_counter()
    if QPS > 0:
        # Open loop: fixed-rate arrivals; completions ride the worker.
        threads = []
        for i in range(QUERIES):
            th = threading.Thread(target=_run_one, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(1.0 / QPS)
        for th in threads:
            th.join(timeout=600)
        mode = "open_loop"
    else:
        # Every query runs even when QUERIES % CLIENTS != 0: the first
        # `rem` clients take one extra (a silent drop would corrupt
        # the logged queries/qps trend).
        base, rem = divmod(QUERIES, max(1, CLIENTS))
        starts = [
            c * base + min(c, rem) for c in range(max(1, CLIENTS) + 1)
        ]

        def _client(c):
            for i in range(starts[c], starts[c + 1]):
                _run_one(i)

        threads = [
            threading.Thread(target=_client, args=(c,), daemon=True)
            for c in range(max(1, CLIENTS))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        mode = "closed_loop"
    wall = time.perf_counter() - t0
    sched.close()

    qs, completed = _hist_latency()
    # Cross-check sample: the ring MAY have evicted under a large
    # sweep (that's fine now — the percentiles above don't read it),
    # but whatever events remain must tell the same story.
    serve_events = obs.events("serve")
    ok = [e["total_s"] for e in serve_events if e["outcome"] == "result"]
    coalesced = int(obs.counter_value("dj_serve_coalesced_total"))
    skew_block, roofline_block = _observatory_summary()
    print(
        json.dumps(
            {
                "metric": "serve_closed_loop_8dev",
                "value": _round(qs[95]) if qs[95] is not None else -1.0,
                "unit": "p95 s/query (CPU trend only, not TPU perf)",
                "mode": mode,
                "rows": ROWS,
                "queries": QUERIES,
                "clients": CLIENTS,
                "qps_submitted": round(QUERIES / wall, 3),
                "completed": completed,
                "coalesced": coalesced,
                "latency_source": "dj_serve_latency_seconds histogram",
                "p50_s": _round(qs[50]),
                "p95_s": _round(qs[95]),
                "p99_s": _round(qs[99]),
                "p95_events_s": _round(_percentile(ok, 95)),
                "events_seen": len(ok),
                "slo": _slo_summary(sched),
                "skew": skew_block,
                "roofline": roofline_block,
                "truth": _truth_block(),
                "truth_armed": _truth_armed(),
                "errors": errors,
                "pressure_level": sched.pressure_level,
            }
        )
    )


def _fleet_workload():
    """The fleet A/B's deterministic three-signature workload. Every
    worker process derives the SAME tables from one fixed seed: plan
    signatures (and so lease keys and manifest records) must match
    across processes for coordination to engage. The three signatures
    come from distinct build-side payload SCHEMAS — a signature covers
    schema and plan, not buffer identity."""
    import dj_tpu
    from dj_tpu.core import table as T

    rows = int(os.environ.get("DJ_SERVE_BENCH_FLEET_ROWS", 20_000))
    rng = np.random.default_rng(23)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    config = dj_tpu.JoinConfig()
    rk = rng.integers(0, rows, rows).astype(np.int64)
    lk = rng.integers(0, rows, rows).astype(np.int64)
    payload_sets = [
        (np.arange(rows, dtype=np.int64),),
        (np.arange(rows, dtype=np.int32),),
        (np.arange(rows, dtype=np.int64),
         np.arange(rows, dtype=np.int32)),
    ]
    builds = [
        dj_tpu.shard_table(topo, T.from_arrays(rk, *cols))
        for cols in payload_sets
    ]
    left, lc = dj_tpu.shard_table(
        topo, T.from_arrays(lk, np.arange(rows, dtype=np.int64))
    )
    return topo, config, builds, left, lc


def fleet_worker():
    """One fleet A/B worker process (``--fleet-worker``): serves its
    query share through an index-backed scheduler — coordinated when
    the parent exported DJ_FLEET_DIR, uncoordinated otherwise — and
    prints ONE JSON line {prepares, latencies_s, outcomes} for the
    parent to pool. A deferred prepare (live peer owns the signature)
    is NOT an error: the scheduler serves that query unprepared, so
    every outcome should be "result" either way."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu.obs as obs
    from dj_tpu.cache import IndexConfig, JoinIndexCache
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    topo, config, builds, left, lc = _fleet_workload()
    queries = int(os.environ.get("DJ_SERVE_BENCH_FLEET_QUERIES", 6))
    idx = JoinIndexCache(IndexConfig(
        hbm_budget_bytes=2e9,
        manifest_path=(
            os.environ.get("DJ_SERVE_BENCH_FLEET_MANIFEST") or None
        ),
    ))
    lat, outcomes = [], {}
    with QueryScheduler(
        ServeConfig(hbm_budget_bytes=4e9, coalesce=False),
        worker=False, index=idx,
    ) as s:
        for i in range(queries):
            bt, bc = builds[i % len(builds)]
            t0 = time.perf_counter()
            try:
                t = s.submit(topo, left, lc, bt, bc, [0], [0], config)
                t.result(timeout=600)
                key = "result"
            except Exception as e:  # noqa: BLE001 - typed terminal
                key = type(e).__name__
            lat.append(time.perf_counter() - t0)
            outcomes[key] = outcomes.get(key, 0) + 1
    prepares = int(obs.counter_value(
        "dj_tenant_prepares_total", tenant="default"
    ))
    idx.clear(force=True)
    print(json.dumps({
        "prepares": prepares,
        "latencies_s": [round(x, 4) for x in lat],
        "outcomes": outcomes,
    }))


def _tenant_flood_arm():
    """Tenant fair-share under synthetic pressure (in-process): a
    flooding tenant's queued work absorbs the sheds when a polite
    tenant arrives at a full queue. Returns (flood_shed_share,
    polite_admitted) — the >= 0.8 absorption evidence in the
    serve_fleet_ab entry."""
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.obs import metrics
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.reset(reenable=True)
    prev = os.environ.get("DJ_FLEET_TENANT_WEIGHTS")
    os.environ["DJ_FLEET_TENANT_WEIGHTS"] = "polite:3,flood:1"
    try:
        rng = np.random.default_rng(29)
        topo = dj_tpu.make_topology(devices=jax.devices()[:8])
        n = 4096
        left, lc = dj_tpu.shard_table(topo, T.from_arrays(
            rng.integers(0, n, n).astype(np.int64),
            np.arange(n, dtype=np.int64),
        ))
        right, rc = dj_tpu.shard_table(topo, T.from_arrays(
            rng.integers(0, n, n).astype(np.int64),
            np.arange(n, dtype=np.int64),
        ))
        # Usage accounting (/tenantz): flood burned ~all the
        # device-seconds, so it is the over-share tenant by any
        # weighting — and its weight is a third of polite's.
        metrics.inc(
            "dj_tenant_device_seconds_total", 100.0, tenant="flood"
        )
        metrics.inc(
            "dj_tenant_device_seconds_total", 1.0, tenant="polite"
        )
        admitted = 0
        with QueryScheduler(
            ServeConfig(queue_depth=6, coalesce=False), worker=False
        ) as s:
            for _ in range(6):
                s.submit(
                    topo, left, lc, right, rc, [0], [0], tenant="flood"
                )
            s._pressure_level = 1  # fair-share arms under pressure
            for _ in range(6):
                try:
                    s.submit(
                        topo, left, lc, right, rc, [0], [0],
                        tenant="polite",
                    )
                    admitted += 1
                except Exception:  # noqa: BLE001 - typed backpressure
                    pass
            s.close()
        series = obs.counter_series("dj_fleet_tenant_shed_total")
        total = sum(series.values())
        flood = sum(
            v for la, v in series.items() if ("tenant", "flood") in la
        )
        share = round(flood / total, 4) if total else None
        return share, admitted
    finally:
        if prev is None:
            os.environ.pop("DJ_FLEET_TENANT_WEIGHTS", None)
        else:
            os.environ["DJ_FLEET_TENANT_WEIGHTS"] = prev


def fleet_ab():
    """K coordinated vs K uncoordinated serve workers (the
    ``serve_fleet_ab`` BENCH_LOG entry; module docstring has the
    design), plus the in-process tenant-flood fair-share arm."""
    import shutil
    import subprocess
    import tempfile

    sigs = 3

    def run_arm(coordinated):
        d = tempfile.mkdtemp(prefix="dj-bench-fleet-")
        env = dict(os.environ)
        env.pop("DJ_FLEET_DIR", None)
        env.pop("DJ_SERVE_BENCH_FLEET_MANIFEST", None)
        if coordinated:
            env["DJ_FLEET_DIR"] = d
            env["DJ_SERVE_BENCH_FLEET_MANIFEST"] = os.path.join(
                d, "manifest.jsonl"
            )
            # A live peer's first build (compile included) can outlast
            # the default bounded lease wait; waiting it out is the
            # coordinated arm's contract — a wait-expiry fallback
            # build would re-introduce the duplicate prepare the arm
            # exists to eliminate.
            env["DJ_FLEET_LEASE_WAIT_S"] = "60"
        procs = [
            subprocess.Popen(
                [
                    sys.executable, os.path.abspath(__file__),
                    "--fleet-worker",
                ],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=env, text=True,
            )
            for _ in range(FLEET_K)
        ]
        lats, prepares, outcomes = [], 0, {}
        try:
            for p in procs:
                out, err = p.communicate(timeout=900)
                line = out.strip().splitlines()[-1] if out.strip() else ""
                if p.returncode != 0 or not line.startswith("{"):
                    raise RuntimeError(
                        f"fleet worker failed (exit {p.returncode}): "
                        f"{err[-2000:]}"
                    )
                rec = json.loads(line)
                lats.extend(rec["latencies_s"])
                prepares += int(rec["prepares"])
                for k, v in rec["outcomes"].items():
                    outcomes[k] = outcomes.get(k, 0) + v
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            shutil.rmtree(d, ignore_errors=True)
        return lats, prepares, outcomes

    un_lat, un_prep, un_out = run_arm(False)
    co_lat, co_prep, co_out = run_arm(True)
    flood_share, polite_admitted = _tenant_flood_arm()
    p95_un = _percentile(un_lat, 95)
    p95_co = _percentile(co_lat, 95)
    print(json.dumps({
        "metric": "serve_fleet_ab",
        "value": (
            round(p95_co / p95_un, 4) if p95_un else None
        ),
        "unit": "coordinated/uncoordinated pooled p95 ratio "
                "(CPU trend only)",
        "fleet": FLEET_K,
        "signatures": sigs,
        "duplicate_prepares": co_prep - sigs,
        "duplicate_prepares_uncoordinated": un_prep - sigs,
        "prepares_coordinated": co_prep,
        "prepares_uncoordinated": un_prep,
        "p95_coordinated_s": _round(p95_co),
        "p95_uncoordinated_s": _round(p95_un),
        "outcomes_coordinated": co_out,
        "outcomes_uncoordinated": un_out,
        "flood_shed_share": flood_share,
        "polite_admitted": polite_admitted,
    }))


def _write_metrics():
    path = os.environ.get("DJ_BENCH_METRICS")
    if not path:
        return
    try:
        import dj_tpu.obs as obs

        obs.write_snapshot(path)
    except Exception as e:  # noqa: BLE001
        print(f"# metrics dump failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


def _write_trace_out():
    """--trace-out PATH: export the newest stored query timeline as
    trace-event JSON (module docstring). Best-effort, after any arm —
    a bench artifact must never fail the bench."""
    if not TRACE_OUT:
        return
    try:
        from dj_tpu.obs import trace as obs_trace

        recent = obs_trace.recent_traces(1)
        if not recent:
            print("# trace-out: no stored query timelines",
                  file=sys.stderr, flush=True)
            return
        qid = recent[0]["query_id"]
        out = obs_trace.export_trace(qid, fmt="perfetto")
        with open(TRACE_OUT, "w") as f:
            json.dump(out, f)
        print(f"# trace-out: wrote query {qid} to {TRACE_OUT}",
              file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"# trace-out failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    try:
        if FLEET_WORKER:
            fleet_worker()
        elif FLEET_K > 0:
            fleet_ab()
        elif OBS_AB:
            obs_ab()
        elif PIPELINE_AB:
            pipeline_ab()
        elif PREPARED_TIER_AB:
            prepared_tier_ab()
        elif AUTOTUNE_AB:
            autotune_ab()
        elif UNIQUE:
            unique_shapes_ab()
        elif HEAVY:
            heavy_hitter_ab()
        elif INDEX_AB:
            index_ab()
        elif TENANTS > 1 or TABLES > 1:
            multi_tenant()
        else:
            main()
    finally:
        _write_metrics()
        _write_trace_out()
