#!/usr/bin/env python
"""Perf-trend regression guard over BENCH_LOG.jsonl.

BENCH_LOG has been a LOG — every kernel-touching commit appends a
datapoint (serve_closed_loop, cpu_mesh_prepared_ab, serve_index_ab,
the headline bench, ...) — but nothing ever read it back, so a
regression only surfaced when a human eyeballed the file. This script
is the guard: for each entry kind it fits a trailing window over the
PRIOR entries and exits nonzero when the NEWEST entry regresses past a
tolerance.

Semantics (deliberately simple and noise-tolerant — CPU-mesh numbers
are host-noise; the trend is the signal):

- Entries group by ``(bench.metric, rows, plan_tier, shape_bucket,
  truth_armed, autotuned, prepared_tier, pipeline)`` —
  the same metric at a different row count is a
  different workload, not a trend point (``rows`` read from the entry
  envelope or the bench JSON, else None). Only those keys and
  ``value`` are read: embedded non-latency blocks (``slo``, ``skew``,
  ``roofline``, and ISSUE 15's ``truth`` reconciliation block) ride
  the envelope and are skipped cleanly by construction. An entry
  produced under a skew-adaptive
  plan tier (``plan_tier``, stamped by serve_bench from the planner's
  decision) never trend-compares against shuffle-only medians; a
  shape-bucketed entry (``shape_bucket``, stamped by serve_bench's
  ``--unique-shapes`` arm) never trend-compares against exact-shape
  medians; and a measured-truth-armed entry (``truth_armed``, stamped
  by serve_bench since it arms DJ_OBS_TRUTH — one extra lower+compile
  per fresh in-window module signature, a deliberate instrumentation
  cost) never trend-compares against unarmed medians; and an
  autotuned entry (``autotuned``, stamped by serve_bench's
  ``--autotune-ab`` arm from the tuner's decision) never
  trend-compares against hand-tuned medians; and a prepared-tier A/B
  entry (``prepared_tier``, stamped by serve_bench's
  ``--prepared-tier-ab`` arm) never trend-compares against
  single-tier medians; and a multi-join pipeline A/B entry
  (``pipeline``, stamped by serve_bench's ``--pipeline-ab`` arm)
  never trend-compares against single-join medians — in each case
  the two run different protocols on purpose.
- Every tracked metric is LOWER-IS-BETTER (elapsed seconds, p95
  latency, cache/no-cache ratios — all of BENCH_LOG today). Error
  entries (``value`` null) and non-positive baselines are skipped.
- Per group with at least ``--min-history`` prior entries: baseline =
  median of the last ``--window`` prior values; regression when
  ``newest > baseline * --tolerance``.
- Exit 0 when every group is clean (or has too little history); exit
  1 with one REGRESSED line per offending group. ci/bench_log.sh runs
  this after appending its entries, so a regressed datapoint fails
  the bench step instead of silently joining the log.

Usage: python scripts/bench_trend.py [--log BENCH_LOG.jsonl]
       [--window 5] [--tolerance 2.0] [--min-history 1]
"""

import argparse
import json
import os
import statistics
import sys


def parse_log(path):
    """BENCH_LOG entries as (group_key, value) streams, in file order.
    Malformed lines and error entries are reported to stderr and
    skipped — the guard judges trends, it does not re-litigate the
    log's append discipline."""
    groups: dict = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                print(
                    f"# bench_trend: skipping malformed line {lineno}",
                    file=sys.stderr,
                )
                continue
            bench = entry.get("bench") or {}
            metric = bench.get("metric")
            value = bench.get("value")
            if metric is None or value is None:
                continue  # error entries never log by contract; belt
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            if value < 0:
                continue  # sentinel (-1 = degenerate serve run)
            rows = entry.get("rows", bench.get("rows"))
            tier = entry.get("plan_tier", bench.get("plan_tier"))
            bucketed = entry.get("shape_bucket", bench.get("shape_bucket"))
            truthed = entry.get("truth_armed", bench.get("truth_armed"))
            tuned = entry.get("autotuned", bench.get("autotuned"))
            ptier = entry.get("prepared_tier", bench.get("prepared_tier"))
            pipe = entry.get("pipeline", bench.get("pipeline"))
            fleet = entry.get("fleet", bench.get("fleet"))
            groups.setdefault(
                (
                    metric, rows, tier, bucketed, truthed, tuned, ptier,
                    pipe, fleet,
                ),
                [],
            ).append(value)
    return groups


def check(groups, *, window, tolerance, min_history):
    """One verdict line per group; returns the list of regressed
    group keys."""
    regressed = []
    for (
        metric, rows, tier, bucketed, truthed, tuned, ptier, pipe, fleet
    ), values in sorted(groups.items(), key=lambda kv: str(kv[0])):
        label = (
            f"{metric}"
            + (f" rows={rows}" if rows is not None else "")
            + (f" plan_tier={tier}" if tier is not None else "")
            + (f" shape_bucket={bucketed}" if bucketed is not None else "")
            + (f" truth_armed={truthed}" if truthed is not None else "")
            + (f" autotuned={tuned}" if tuned is not None else "")
            + (f" prepared_tier={ptier}" if ptier is not None else "")
            + (f" pipeline={pipe}" if pipe is not None else "")
            + (f" fleet={fleet}" if fleet is not None else "")
        )
        prior, newest = values[:-1], values[-1]
        if len(prior) < min_history:
            print(
                f"SKIP      {label}: {len(values)} entries "
                f"(need {min_history + 1} for a trend)"
            )
            continue
        baseline = statistics.median(prior[-window:])
        if baseline <= 0:
            print(f"SKIP      {label}: non-positive baseline {baseline}")
            continue
        ratio = newest / baseline
        verdict = "REGRESSED" if ratio > tolerance else "ok"
        print(
            f"{verdict:<9} {label}: latest {newest:g} vs trailing-"
            f"median {baseline:g} (x{ratio:.3f}, tolerance "
            f"x{tolerance:g}, n={len(values)})"
        )
        if verdict == "REGRESSED":
            regressed.append(label)
    return regressed


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--log", default=os.path.join(repo, "BENCH_LOG.jsonl"),
        help="path to the BENCH_LOG.jsonl to judge",
    )
    p.add_argument(
        "--window", type=int, default=5,
        help="trailing prior entries the baseline median covers",
    )
    p.add_argument(
        "--tolerance", type=float, default=2.0,
        help="regression threshold: latest > median * tolerance fails "
             "(default 2.0 — CPU-mesh entries are host-noise; the "
             "guard catches cliffs, not jitter)",
    )
    p.add_argument(
        "--min-history", type=int, default=1,
        help="minimum PRIOR entries a group needs before it is judged",
    )
    args = p.parse_args(argv)
    if not os.path.exists(args.log):
        print(f"bench_trend: no log at {args.log} (nothing to judge)")
        return 0
    groups = parse_log(args.log)
    if not groups:
        print("bench_trend: log holds no trend points")
        return 0
    regressed = check(
        groups,
        window=max(1, args.window),
        tolerance=args.tolerance,
        min_history=max(1, args.min_history),
    )
    if regressed:
        print(
            f"bench_trend: {len(regressed)} regressed group(s): "
            f"{', '.join(regressed)}",
            file=sys.stderr,
        )
        return 1
    print("bench_trend: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
