"""Chaos soak: the scheduler survives every fault family, provably.

Replays a fixed query mix — unprepared join, prepared singleton, a
coalescable pair, a zero-deadline query, an over-budget submit, and a
HEAVY-HITTER skewed probe (50% of its rows on 3 hot keys, with the
DJ_OBS_SKEW probe armed — the skew gauges/events must fire, its heals
must stay typed, and its trace must still close) — while walking
deterministic fault injection (DJ_FAULT semantics via
faults.configure, no RNG) through EVERY site family the serving path
consults:

- flag sites: join.* / prepared.* / prepare.* overflow + plan-mismatch
  forcing (heal-ladder and re-prepare paths under the scheduler), and
- exception sites: module_build / communicator (build-time failures
  hitting the dispatch loop), broadcast / salted (the skew-adaptive
  plan tiers failing at build — the ladder must pin adapt and retry
  on shuffle), plus repeated-fire specs that exhaust the heal budget
  into CapacityExhausted.

The walk runs with the adaptive planner ARMED (DJ_PLAN_ADAPT=1, a
byte threshold that broadcasts the small build side, a lowered salt
ratio): the mix keeps one broadcast and one salted signature live
every iteration, and the summary asserts both tiers actually engaged.

It also runs with the per-signature plan autotuner ARMED
(DJ_AUTOTUNE=1, PR 16) and walks its two fault sites (autotune_probe
/ autotune_apply): every iteration asserts zero duplicate tunes per
signature, and the faulted iterations assert exactly one "autotune"
ladder pin with every query still returning a result — a tuner
failure must cost the tuned knobs, never the query.

And it runs with the prepared BUILD tiers armed (DJ_PREPARED_TIER=auto,
PR 17): a broadcast-prepared and a salted-prepared side stay live in
the mix every iteration, and the walk covers the five new sites —
probe_expand (trace-time expansion-kernel failure pins the "expand"
ladder and retraces the histogram baseline; exercised via a
fresh-shape query so the trace actually happens), bc_prepared_query /
salted_prepared_query (dispatch-time faults pin "prepared_tier" and
re-prepare on shuffle), and prepare_broadcast / prepare_salted
(replication faults DURING prepare demote to a shuffle-prepared side
that must still serve row-exact). Each faulted iteration asserts
exactly one pin of the site's own tier and zero FaultInjected
terminals; the walk-level summary asserts both replication tiers
actually engaged and their strict HLO contracts each passed.

The invariants asserted for every submitted query, every iteration:

  EXACTLY ONE terminal state — a correct result (row count checked
  against the numpy oracle), or a typed DJError (AdmissionRejected /
  QueueFull / DeadlineExceeded / CapacityExhausted / FaultInjected /
  BackendError / PlanMismatch) — within the timeout. Zero hangs, zero
  bare exceptions, zero double-finishes (the scheduler asserts the
  single-transition invariant internally).

  AND a COMPLETE query trace (PR 8): ``obs.query_trace(query_id)``
  must hold a closed submit-to-terminal timeline for every one of the
  walk's queries — door sheds included (the raised error carries
  ``.query_id``) — with the terminal ``query`` span present, zero
  orphan spans, and a terminal ``serve`` event for every ticketed
  query. Healing, re-preparing, and faulting under every site family
  is exactly the load that used to evict per-query history from the
  shared ring; the timeline store must survive it.

The walk also arms the measured-truth layer (ISSUE 15:
DJ_OBS_TRUTH=1 + DJ_SERVE_MEASURED_HBM=1) and asserts its invariants
at the end: every builder that compiled a fresh module reported an
``xla_cost`` truth record, every model/XLA reconciliation ratio is
finite and positive, and the measured-HBM admission gate stayed a
graceful no-op on this memory_stats-less backend (zero measured
rejects, zero crashes).

Exit code 0 + one JSON summary line on success; nonzero with the
violation on failure. tests/test_serve.py::test_chaos_soak_slice runs
a fast 3-site slice of exactly this loop in CI; this script is the
full walk (a few minutes on the 8-device CPU mesh).

The walk also covers the fleet coordination sites (PR 20):
``fleet.lease_acquire`` / ``fleet.lease_heartbeat`` /
``fleet.publish`` iterations arm a throwaway ``DJ_FLEET_DIR`` and an
index cache in front of the scheduler so the faulted site fires
inside the real prepare gate / budget publish — each must pin the
ladder's ``fleet`` tier exactly once and degrade to process-local
serving (typed results throughout, never a deadlock).

``--fleet`` (DJ_SOAK_FLEET=1) runs the PR-20 crash-tolerant
coordination drill instead: real subprocess peers sharing one
``DJ_FLEET_DIR`` under a short lease TTL. Phase 1 — a live peer
finishes a prepare and stays resident: the parent's identical submit
must DEFER (one ``dj_fleet_peer_defer_total``, zero duplicate
prepares) and still serve the query row-exact, unprepared. Phase 2 —
a peer is SIGKILLed while HOLDING the prepare lease mid-"build": the
survivor must reclaim the stale lease (exactly one
``dj_fleet_lease_reclaimed_total``) and build the side itself.
Phase 3 — a peer settles a HEALED plan into the shared manifest and
dies: the survivor must REPLAY the dead owner's settled factors
(``dj_fleet_replay_total``, zero prepare-stage heal events, byte-same
factors in both manifest records) instead of re-paying the heal
ladder. Every query a typed terminal; zero hangs.

``--hard-death`` (DJ_SOAK_HARD_DEATH=1) runs the PR-19 crash-forensics
arm instead: a CHILD process (this script re-exec'd with
``--hard-death-child``) arms the DJ_OBS_BLACKBOX bundle, submits live
queries through a real scheduler, and SIGTERMs itself mid-query — the
way a preempted fleet worker actually dies. The parent then audits
the post-mortem evidence: the child died BY the signal (no bare
traceback anywhere), exactly one bundle exists, its ``meta`` section
says sigterm, the dead queries' timelines are present with the open
``query`` span marked incomplete, and ``scripts/blackbox_read.py``
exits 0 naming the dead query. The fault walk proves the scheduler
survives faults; this arm proves the OBSERVATORY survives the
scheduler's death.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("DJ_SOAK_ROWS", 2048))
TIMEOUT_S = float(os.environ.get("DJ_SOAK_TIMEOUT_S", 600))

# The walk: every site family the serving path consults, each with an
# exact-call spec (and one repeated-fire spec per stage to push a heal
# ladder into typed exhaustion).
FAULT_WALK = (
    None,  # baseline iteration: no faults, everything must be a result
    "module_build@call=1",
    "communicator@call=1",
    "join.join_overflow@call=1",
    "join.shuffle_overflow@call=1",
    "join.char_overflow@call=1",
    ",".join(f"join.join_overflow@call={i}" for i in range(1, 5)),
    "prepared.join_overflow@call=1",
    "prepared.char_overflow@call=1",
    "prepared.prepared_plan_mismatch@call=1",
    ",".join(f"prepared.join_overflow@call={i}" for i in range(1, 5)),
    # plan-mismatch forces a RE-prepare whose build then hits a forced
    # shuffle overflow: the prepare.* family exercised on the live
    # re-preparation path, under the scheduler.
    "prepared.prepared_plan_mismatch@call=1,prepare.shuffle_overflow@call=1",
    # Skew-adaptive plan tiers (PR 12): a broadcast / salted module
    # build failing at trace time must pin the ladder's "adapt"
    # baseline and retry the query on the shuffle plan — typed result,
    # never a hang (the mix below keeps one broadcast-eligible and one
    # salted signature live every iteration).
    "broadcast@call=1",
    "salted@call=1",
    # Per-signature plan autotuner (PR 16): a faulted probe dispatch
    # or a faulted config application must pin the ladder's "autotune"
    # baseline (exactly one degrade event, asserted below) and the
    # retry must serve the hand-tuned config — every query still a
    # typed result, never a hang.
    "autotune_probe@call=1",
    "autotune_apply@call=1",
    # Prepared BUILD tiers + probe expansion (PR 17). probe_expand: a
    # trace-time failure in the segment-offset expansion must pin the
    # ladder's "expand" baseline (the legacy histogram chain) and
    # retrace — the iteration submits a FRESH-shape prepared query so
    # the site is actually consulted (cached modules never re-trace).
    # bc_/salted_prepared_query: a dispatch-time failure on a live
    # broadcast-/salted-prepared side must pin "prepared_tier" and
    # surface the structural PlanMismatch that re-prepares on the
    # shuffle baseline. prepare_broadcast/_salted: a replication-tier
    # build failure DURING prepare must pin the same ladder and hand
    # back a demoted shuffle-prepared side that still serves row-exact
    # results. Each asserts exactly one degrade pin and zero
    # FaultInjected terminals below.
    "probe_expand@call=1",
    "bc_prepared_query@call=1",
    "salted_prepared_query@call=1",
    "prepare_broadcast@call=1",
    "prepare_salted@call=1",
    # Fleet coordination sites (PR 20), armed per-iteration: a tmp
    # DJ_FLEET_DIR plus an index cache on the scheduler routes the
    # mix's Table-right submits through the fleet prepare gate, so
    # each site is consulted on the live serving path. A fleet.*
    # fault must pin the ladder's "fleet" tier EXACTLY once and the
    # retry must land process-local — coordination degrades, it
    # never deadlocks and never surfaces as a query terminal.
    "fleet.lease_acquire@call=1",
    "fleet.lease_heartbeat@call=1",
    "fleet.publish@call=1",
)

# The PR-17 sites walked above: site -> the ladder tier a fault must
# pin (exactly once per faulted iteration, asserted in the loop).
NEW_TIER_SITES = {
    "probe_expand": "expand",
    "bc_prepared_query": "prepared_tier",
    "salted_prepared_query": "prepared_tier",
    "prepare_broadcast": "prepared_tier",
    "prepare_salted": "prepared_tier",
}

# The PR-20 fleet coordination sites: walked with DJ_FLEET_DIR armed
# for that iteration only (fleet mode is otherwise off in the walk);
# each fault must pin the "fleet" ladder tier exactly once.
FLEET_SITES = ("fleet.lease_acquire", "fleet.lease_heartbeat", "fleet.publish")

ALLOWED = (
    "result", "AdmissionRejected", "QueueFull", "DeadlineExceeded",
    "CapacityExhausted", "FaultInjected", "BackendError", "PlanMismatch",
)


def main() -> int:
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8; "
        f"got {jax.devices()}"
    )
    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu import fleet as fleet_mod
    from dj_tpu.cache import IndexConfig, JoinIndexCache
    from dj_tpu.core import table as T
    from dj_tpu.resilience import errors as resil
    from dj_tpu.resilience import faults
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.resilience.errors import (
        AdmissionRejected,
        DJError,
        QueueFull,
    )
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    # Arm the measured partition-skew probe UNCONDITIONALLY (an
    # inherited DJ_OBS_SKEW=0 must not turn the soak's skew invariant
    # into a spurious red): the skewed query below must light the
    # skew gauges/events up, and every query's probe rides its
    # timeline (one extra tiny dispatch per query — the soak is
    # exactly the place to pay it).
    os.environ["DJ_OBS_SKEW"] = "1"
    # Arm the skew-adaptive planner for the whole walk (PR 12): the
    # broadcast-eligible signature (small build side, fits the byte
    # threshold) and the heavy-hitter signature (salts under the
    # lowered ratio threshold) keep BOTH adaptive tiers engaged every
    # iteration, so the new fault sites actually fire and the
    # skewed-mix invariant below can assert engagement. The threshold
    # fits the small broadcast build side (~a few KB replicated) but
    # not the 2048-row mix tables.
    os.environ["DJ_PLAN_ADAPT"] = "1"
    os.environ["DJ_BROADCAST_BYTES"] = "8000"
    os.environ["DJ_SALT_RATIO"] = "1.3"
    # Contract audit armed STRICT for the entire walk (ISSUE 13):
    # every fresh module any fault iteration traces is audited against
    # its tier's declarative HLO contract (dj_tpu/analysis/contracts
    # via obs.cached_build) — a violation raises the typed
    # ContractViolation (an un-ALLOWED outcome below) AND is asserted
    # zero from the counters at the end. The probe merge tier is armed
    # so the walk's prepared/coalesced queries exercise the probe
    # contract alongside the broadcast, salted, and packed-shuffle
    # contracts; heals/pins that retrace under xla re-audit against
    # THAT tier's contract, so the walk covers both.
    os.environ["DJ_HLO_AUDIT"] = "strict"
    os.environ["DJ_JOIN_MERGE"] = "probe"
    # Measured-truth layer armed for the whole walk (ISSUE 15): every
    # fresh module any iteration compiles must report XLA cost/memory
    # truth (asserted from the never-evicting counters below), modules
    # compiling inside a dispatch reconcile the admission forecast
    # into dj_model_xla_ratio, and the measured-HBM admission gate is
    # armed on a backend WITHOUT memory_stats (the CPU mesh) — the
    # pinned graceful no-op: the entire walk must behave exactly as if
    # the gate were unarmed, zero crashes.
    os.environ["DJ_OBS_TRUTH"] = "1"
    os.environ["DJ_SERVE_MEASURED_HBM"] = "1"
    # Prepared build tiers armed for the whole walk (PR 17): "auto"
    # lets the prepare-time planner decide — the tiny build side below
    # fits the replicated budget and prepares BROADCAST (zero-
    # collective query modules, audited strict), the heavy-hitter
    # build side salts its resident runs, and the 2048-row mix tables
    # stay shuffle-prepared. The env must be armed (not just the
    # per-side tier) so the degradation ladder treats "prepared_tier"
    # as an active tier and PINS it on the new fault sites instead of
    # letting FaultInjected surface.
    os.environ["DJ_PREPARED_TIER"] = "auto"
    # Per-signature plan autotuner armed for the whole walk (PR 16):
    # every fresh signature tunes ONCE (candidate pricing + top-2
    # probe dispatches) before serving — the per-iteration invariant
    # below pins zero duplicate tunes per signature, and the
    # autotune_* fault iterations must demote to hand-tuned defaults
    # with exactly one ladder pin while still returning results.
    os.environ["DJ_AUTOTUNE"] = "1"
    from dj_tpu.parallel import autotune
    rng = np.random.default_rng(7)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    lk = rng.integers(0, 500, ROWS).astype(np.int64)
    rk = rng.integers(0, 500, ROWS).astype(np.int64)
    left, lc = dj_tpu.shard_table(
        topo, T.from_arrays(lk, np.arange(ROWS, dtype=np.int64))
    )
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(ROWS, dtype=np.int64))
    )
    # Heavy-hitter probe: 50% of rows concentrated on 3 hot keys — the
    # classic skew shape the shuffle's destination buckets hate. Its
    # join output is much larger than the uniform mix's, so its heals
    # (bucket/join-out growth) must stay typed under every fault site.
    hot = np.array([7, 211, 499], dtype=np.int64)
    lk_skew = rng.integers(0, 500, ROWS).astype(np.int64)
    hot_mask = rng.random(ROWS) < 0.5
    lk_skew[hot_mask] = hot[rng.integers(0, len(hot), int(hot_mask.sum()))]
    # Extra payload column: plan decisions are per plan SIGNATURE
    # (schema-level), and the skewed mix must salt on ITS signature
    # without pinning the uniform mix's plan.
    left_skew, lsc = dj_tpu.shard_table(
        topo, T.from_arrays(lk_skew, np.arange(ROWS, dtype=np.int64),
                            np.arange(ROWS, dtype=np.int64)),
    )
    # Broadcast-eligible build side: small (fits DJ_BROADCAST_BYTES
    # replicated) with an int32 payload so its SIGNATURE is distinct
    # from the 2048-row mix tables' — the planner decides broadcast
    # for this signature and shuffle for theirs.
    rk_small = rng.integers(0, 500, 128).astype(np.int64)
    right_small, rsc = dj_tpu.shard_table(
        topo, T.from_arrays(rk_small, np.arange(128, dtype=np.int32))
    )
    # Broadcast-PREPARED build side (PR 17): tiny enough that its
    # replicated footprint (bytes x world) fits DJ_BROADCAST_BYTES, so
    # the auto planner prepares it broadcast and every query against
    # it dispatches the zero-collective module (audited against the
    # bc_prepared_query contract under the strict walk).
    rk_tiny = rng.integers(0, 500, 32).astype(np.int64)
    right_tiny, rtc = dj_tpu.shard_table(
        topo, T.from_arrays(rk_tiny, np.arange(32, dtype=np.int64))
    )
    # Salted-PREPARED build side: the heavy-hitter shape on the BUILD
    # side this time — the prepare-time skew probe names the heavy
    # resident partitions and replicates them to rotated peers. The
    # extra payload column keeps its plan SIGNATURE distinct from the
    # uniform 2048-row build's (tier decisions are per signature; a
    # shared one would replay the uniform side's shuffle record).
    rk_hot = rng.integers(0, 500, ROWS).astype(np.int64)
    hot_mask_r = rng.random(ROWS) < 0.5
    rk_hot[hot_mask_r] = hot[
        rng.integers(0, len(hot), int(hot_mask_r.sum()))
    ]
    right_hot, rhc = dj_tpu.shard_table(
        topo, T.from_arrays(rk_hot, np.arange(ROWS, dtype=np.int64),
                            np.arange(ROWS, dtype=np.int64)),
    )
    # Fresh-shape probe table for the probe_expand iteration: a row
    # count no other query uses, so its prepared-query module has
    # never been traced when the fault arms — the trace-time site
    # actually fires (a cached module would silently skip it). Smaller
    # than the prepared left capacity so the tag width still fits.
    FRESH_ROWS = ROWS // 2
    lk_fresh = rng.integers(0, 500, FRESH_ROWS).astype(np.int64)
    left_fresh, lfc = dj_tpu.shard_table(
        topo, T.from_arrays(lk_fresh, np.arange(FRESH_ROWS, dtype=np.int64))
    )

    def _oracle(lkeys):
        return int(
            sum(
                (lkeys == k).sum() * (rk == k).sum()
                for k in np.unique(rk)
            )
        )

    oracle = _oracle(lk)
    oracle_skew = _oracle(lk_skew)
    oracle_fresh = int(
        sum(
            (lk_fresh == k).sum() * (rk == k).sum()
            for k in np.unique(rk)
        )
    )
    oracle_bc = int(
        sum(
            (lk == k).sum() * (rk_small == k).sum()
            for k in np.unique(rk_small)
        )
    )
    oracle_tiny = int(
        sum(
            (lk == k).sum() * (rk_tiny == k).sum()
            for k in np.unique(rk_tiny)
        )
    )
    oracle_hot = int(
        sum(
            (lk == k).sum() * (rk_hot == k).sum()
            for k in np.unique(rk_hot)
        )
    )
    # Multi-join pipeline oracle (PR 18): left ⋈ right ⋈ right_tiny,
    # both stages on key column 0 — composed per-key match products.
    oracle_pipe = int(
        sum(
            (lk == k).sum() * (rk == k).sum() * (rk_tiny == k).sum()
            for k in np.unique(rk_tiny)
        )
    )
    cfg = dj_tpu.JoinConfig(bucket_factor=4.0, join_out_factor=4.0)
    prep = dj_tpu.prepare_join_side(
        topo, right, rc, [0], cfg, left_capacity=left.capacity
    )
    # The two replication-tier prepared sides stay live across the
    # whole walk (their tier is a property of the side object, not of
    # the per-iteration ledger): every iteration serves one broadcast-
    # prepared and one salted-prepared query, so the new dispatch
    # fault sites are consulted under every OTHER fault family too.
    prep_bc = dj_tpu.prepare_join_side(
        topo, right_tiny, rtc, [0], cfg, left_capacity=left.capacity
    )
    prep_salt = dj_tpu.prepare_join_side(
        topo, right_hot, rhc, [0], cfg, left_capacity=left.capacity
    )
    assert prep.tier == "shuffle", prep.tier
    assert prep_bc.tier == "broadcast", (
        f"auto planner did not broadcast the tiny build side "
        f"(got {prep_bc.tier})"
    )
    assert prep_salt.tier == "salted", (
        f"auto planner did not salt the heavy-hitter build side "
        f"(got {prep_salt.tier})"
    )

    tally: dict[str, int] = {}
    violations: list[str] = []
    all_qids: list[tuple] = []  # (query_id, ticketed) for every submit
    t_start = time.perf_counter()
    for spec in FAULT_WALK:
        # Fresh serving state per iteration: faults and learned factors
        # from one site must not mask the next site's behavior; tier
        # pins reset so a degrade in one iteration is observable in
        # another.
        faults.reset()
        dj_ledger.reset()
        resil.reset_pins()
        # Fresh tuner state too (in-memory decisions, flags, windows):
        # each iteration must TUNE its signatures anew so the autotune
        # fault sites actually fire and the duplicate-tune invariant
        # judges one iteration, not replays from the last.
        autotune._clear()
        at_events_before = len(obs.events("tune"))
        at_degrades_before = int(obs.counter_value(
            "dj_degrade_total", tier="autotune"
        ))
        fi_before = tally.get("FaultInjected", 0)
        # PR-17 site bookkeeping: the new-site iterations assert
        # exactly one pin of the site's own ladder tier.
        new_site = None
        if spec is not None and "," not in spec:
            s0 = spec.split("@", 1)[0]
            if s0 in NEW_TIER_SITES:
                new_site = s0
        nt_degrades_before = {
            t: int(obs.counter_value("dj_degrade_total", tier=t))
            for t in ("expand", "prepared_tier")
        }
        # PR-20 fleet-site iterations run with coordination ARMED (a
        # throwaway shared dir) and the scheduler fronted by an index
        # cache, so the faulted fleet.* site fires inside the real
        # prepare gate / budget publish — not a synthetic call.
        fleet_site = None
        if spec is not None and "," not in spec:
            s0 = spec.split("@", 1)[0]
            if s0 in FLEET_SITES:
                fleet_site = s0
        fleet_idx = None
        fl_degrades_before = int(obs.counter_value(
            "dj_degrade_total", tier="fleet"
        ))
        if fleet_site is not None:
            fleet_mod.reset()
            fdir = tempfile.mkdtemp(prefix="dj-soak-fleet-")
            os.environ["DJ_FLEET_DIR"] = fdir
            fleet_idx = JoinIndexCache(IndexConfig(
                hbm_budget_bytes=50e6,
                manifest_path=os.path.join(fdir, "manifest.jsonl"),
            ))
        if spec is not None:
            faults.configure(spec)
        # probe_expand is a TRACE-time site and the autotuner prices
        # candidates by tracing them: with the tuner armed, the fresh
        # signature's default (segment) candidate traces inside
        # price_plan_candidate, the fault fires THERE, and the tuner
        # swallows it as an "infeasible candidate" — picking hist and
        # serving row-exact with zero degrade pins. That is the
        # tuner's own (correct) resilience story, but it starves the
        # ladder assertion below, so this one iteration dispatches
        # with the tuner off: the fault then reaches the dispatch
        # degrade_guard, which must pin "expand".
        if new_site == "probe_expand":
            os.environ["DJ_AUTOTUNE"] = "0"
        # The prepare-time replication sites only fire DURING a
        # broadcast/salted prepare: run one under the armed fault.
        # The ladder must pin "prepared_tier" and hand back a DEMOTED
        # shuffle-prepared side — which must still serve row-exact.
        demoted = None
        demoted_oracle = None
        if new_site == "prepare_broadcast":
            demoted = dj_tpu.prepare_join_side(
                topo, right_tiny, rtc, [0], cfg,
                left_capacity=left.capacity,
            )
            demoted_oracle = oracle_tiny
        elif new_site == "prepare_salted":
            demoted = dj_tpu.prepare_join_side(
                topo, right_hot, rhc, [0], cfg,
                left_capacity=left.capacity,
            )
            demoted_oracle = oracle_hot
        if demoted is not None and demoted.tier != "shuffle":
            violations.append(
                f"{spec}: faulted prepare returned tier "
                f"{demoted.tier!r}, expected the demoted shuffle "
                f"baseline"
            )
        with QueryScheduler(
            ServeConfig(hbm_budget_bytes=50e6, max_attempts=3),
            index=fleet_idx,
        ) as sched:
            tickets = []
            door_sheds = 0

            def _submit(*args, expected=None, submit_fn=None, **kw):
                nonlocal door_sheds
                try:
                    t = (submit_fn or sched.submit)(*args, **kw)
                    tickets.append((t, expected))
                    all_qids.append((t.query_id, True))
                except (AdmissionRejected, QueueFull) as e:
                    # Typed shed AT the door is a legal terminal state
                    # — and its trace must close too (submit tags the
                    # error with the minted query_id).
                    door_sheds += 1
                    qid = getattr(e, "query_id", None)
                    if qid is None:
                        violations.append(
                            f"door shed without query_id: {e}"
                        )
                    else:
                        all_qids.append((qid, False))
                    tally[type(e).__name__] = (
                        tally.get(type(e).__name__, 0) + 1
                    )

            # The mix: unprepared, prepared singleton, a coalescable
            # pair, a heavy-hitter skewed probe (salts under the
            # adaptive planner), a broadcast-eligible small build
            # side, a multi-join pipeline, a dead-on-arrival
            # deadline, an over-budget config.
            _submit(topo, left, lc, right, rc, [0], [0], cfg,
                    expected=oracle)
            _submit(topo, left, lc, prep, None, [0], None, cfg,
                    expected=oracle)
            _submit(topo, left, lc, prep, None, [0], None, cfg,
                    expected=oracle)
            _submit(topo, left_skew, lsc, right, rc, [0], [0], cfg,
                    expected=oracle_skew)
            _submit(topo, left, lc, right_small, rsc, [0], [0], cfg,
                    expected=oracle_bc)
            # PR 17: one broadcast-prepared and one salted-prepared
            # query EVERY iteration — the replication-tier dispatch
            # sites (and their strict HLO contracts) are consulted
            # under every fault family, not just their own.
            _submit(topo, left, lc, prep_bc, None, [0], None, cfg,
                    expected=oracle_tiny)
            _submit(topo, left, lc, prep_salt, None, [0], None, cfg,
                    expected=oracle_hot)
            # PR 18: one multi-join pipeline query EVERY iteration —
            # the chain admits and serves as ONE query (pipe[...]
            # signature, per-stage heal), its dim stage elides
            # collectives through the broadcast tier, and every fault
            # family must surface through the same typed terminals
            # with a complete one-query trace.
            _submit(topo, left, lc,
                    [dj_tpu.JoinStage(right=right, right_counts=rc,
                                      left_on=(0,), right_on=(0,)),
                     dj_tpu.JoinStage(right=right_tiny,
                                      right_counts=rtc,
                                      left_on=(0,), right_on=(0,))],
                    cfg, expected=oracle_pipe,
                    submit_fn=sched.submit_pipeline)
            if new_site == "probe_expand":
                # Fresh shape -> fresh trace -> the trace-time site
                # actually fires (see FAULT_WALK comment).
                _submit(topo, left_fresh, lfc, prep, None, [0], None,
                        cfg, expected=oracle_fresh)
            if demoted is not None:
                _submit(topo, left, lc, demoted, None, [0], None, cfg,
                        expected=demoted_oracle)
            _submit(topo, left, lc, right, rc, [0], [0], cfg,
                    deadline_s=0.0, expected=oracle)
            _submit(topo, left, lc, right, rc, [0], [0],
                    dj_tpu.JoinConfig(join_out_factor=1e9),
                    expected=oracle)
            for t, expected in tickets:
                label = None
                try:
                    r = t.result(timeout=TIMEOUT_S)
                    label = "result"
                    got = int(np.asarray(r[1]).sum())
                    if got != expected:
                        violations.append(
                            f"{spec}: wrong rows {got} != {expected}"
                        )
                except TimeoutError:
                    violations.append(f"{spec}: HANG (query #{t.seq})")
                    continue
                except DJError as e:
                    label = type(e).__name__
                except BaseException as e:  # noqa: BLE001
                    violations.append(
                        f"{spec}: BARE exception {type(e).__name__}: {e}"
                    )
                    continue
                if not t.done:
                    violations.append(f"{spec}: no terminal state")
                if label not in ALLOWED:
                    violations.append(f"{spec}: unexpected {label}")
                tally[label] = tally.get(label, 0) + 1
        if new_site == "probe_expand":
            os.environ["DJ_AUTOTUNE"] = "1"  # re-arm for the walk
        # Zero duplicate tunes per signature THIS iteration (PR 16):
        # resolve()'s in-flight set makes concurrent same-signature
        # dispatches serve defaults instead of racing a second tune,
        # and a tuned decision replays in-memory thereafter. (Ring
        # slicing: evictions only shrink the old prefix, so the slice
        # never misattributes a prior iteration's tune events.)
        fresh_tunes = obs.events("tune")[at_events_before:]
        tuned_sigs = [
            e.get("sig") for e in fresh_tunes
            if e.get("action") == "tune"
        ]
        dupes = {s for s in tuned_sigs if tuned_sigs.count(s) > 1}
        if dupes:
            violations.append(
                f"{spec}: duplicate tune(s) for signature(s) "
                f"{sorted(dupes)}"
            )
        if spec is not None and spec.startswith("autotune_"):
            # A faulted probe/apply must pin the autotune baseline
            # EXACTLY once and the retry must still serve results —
            # the fault never surfaces as a terminal.
            at_degrades = int(obs.counter_value(
                "dj_degrade_total", tier="autotune"
            )) - at_degrades_before
            if at_degrades != 1:
                violations.append(
                    f"{spec}: expected exactly one autotune degrade "
                    f"pin, saw {at_degrades}"
                )
            if tally.get("FaultInjected", 0) != fi_before:
                violations.append(
                    f"{spec}: an autotune fault surfaced as a "
                    f"terminal FaultInjected instead of degrading"
                )
        if new_site is not None:
            # A PR-17 site fault must pin its own ladder tier EXACTLY
            # once and never surface as a terminal FaultInjected —
            # the expansion kernel retraces under the histogram
            # baseline; the prepared tiers re-prepare (or rebuild)
            # on the shuffle baseline.
            want_tier = NEW_TIER_SITES[new_site]
            nt_degrades = int(obs.counter_value(
                "dj_degrade_total", tier=want_tier
            )) - nt_degrades_before[want_tier]
            if nt_degrades != 1:
                violations.append(
                    f"{spec}: expected exactly one {want_tier!r} "
                    f"degrade pin, saw {nt_degrades}"
                )
            if tally.get("FaultInjected", 0) != fi_before:
                violations.append(
                    f"{spec}: a {new_site} fault surfaced as a "
                    f"terminal FaultInjected instead of degrading"
                )
        if fleet_site is not None:
            # A fleet.* fault must pin the "fleet" tier EXACTLY once
            # (process-local fallback) and never surface as a query
            # terminal — the iteration completing at all is the
            # no-deadlock proof (bounded lease waits).
            fl_degrades = int(obs.counter_value(
                "dj_degrade_total", tier="fleet"
            )) - fl_degrades_before
            if fl_degrades != 1:
                violations.append(
                    f"{spec}: expected exactly one 'fleet' degrade "
                    f"pin, saw {fl_degrades}"
                )
            if tally.get("FaultInjected", 0) != fi_before:
                violations.append(
                    f"{spec}: a fleet fault surfaced as a terminal "
                    f"FaultInjected instead of degrading"
                )
            # Disarm: unpin FIRST (reset_pins restores the env knob it
            # overwrote — DJ_FLEET_DIR), then drop the knob so later
            # iterations run fleet-off, then forget process-local
            # coordination state (drain handler, publish throttle).
            resil.reset_pins()
            os.environ.pop("DJ_FLEET_DIR", None)
            try:
                fleet_idx.clear(force=True)
            except Exception:  # noqa: BLE001 - disarm must disarm the rest
                pass
            fleet_mod.reset()
    # Trace-completeness invariant (module docstring): EVERY submitted
    # query — across every fault family, door sheds included — must
    # reconstruct to a complete timeline. The walk is exactly the load
    # that evicts per-query history from the shared ring; the timeline
    # store must not care.
    traces_complete = 0
    for qid, ticketed in all_qids:
        tr = obs.query_trace(qid)
        if tr is None:
            violations.append(f"trace MISSING for {qid}")
        elif not tr["complete"] or tr["orphans"]:
            violations.append(
                f"INCOMPLETE trace {qid}: orphans={tr['orphans']}, "
                f"spans={tr['spans']}"
            )
        elif ticketed and tr["terminal"] is None:
            violations.append(f"no terminal serve event for {qid}")
        else:
            traces_complete += 1
    # Skew-observatory invariant: the heavy-hitter mix ran under an
    # armed DJ_OBS_SKEW probe in EVERY iteration, so the measured-skew
    # aggregates must show (a) batches observed and (b) a max/mean
    # destination ratio clearly above uniform — if either is missing,
    # the probe went dark and the skew signal is untrustworthy.
    sk = obs.skew.summary()
    if sk["batches"] == 0:
        violations.append("skew probe armed but no skew events fired")
    elif sk["max_ratio"] < 1.2:
        violations.append(
            f"heavy-hitter mix observed max skew ratio only "
            f"{sk['max_ratio']} (expected > 1.2)"
        )
    # Skewed-mix ADAPTIVE invariant (PR 12): with the planner armed
    # for the whole walk, both adaptive tiers must actually have
    # ENGAGED — the broadcast-eligible signature decided broadcast and
    # the heavy-hitter signature decided salted at least once (read
    # from the counters, which never evict, not the bounded ring).
    tiers_engaged = {
        dict(labels).get("tier")
        for labels in obs.counter_series("dj_plan_adapt_total")
    }
    for want_tier in ("broadcast", "salted"):
        if want_tier not in tiers_engaged:
            violations.append(
                f"adaptive planner armed but the {want_tier} tier "
                f"never engaged (tiers seen: {sorted(tiers_engaged)})"
            )
    # HLO-contract invariant (ISSUE 13): the whole walk ran under
    # DJ_HLO_AUDIT=strict — zero violated audits, and the probe,
    # broadcast, and packed-plan contracts must each have PASSED at
    # least once (an audit that never fired is a silent hole, not a
    # pass; counters never evict, unlike the bounded ring).
    audits: dict[tuple, float] = {}
    for labels, v in obs.counter_series("dj_hlo_audit_total").items():
        d = dict(labels)
        audits[(d.get("contract"), d.get("verdict"))] = v
    violated = {k[0]: v for k, v in audits.items() if k[1] != "pass"}
    if violated:
        violations.append(
            f"HLO contract violations under strict audit: {violated}"
        )
    for want in ("probe_query", "broadcast_query",
                 "shuffle_packed_plan", "bc_prepared_query",
                 "salted_prepared_query"):
        if audits.get((want, "pass"), 0) <= 0:
            violations.append(
                f"strict audit armed but the {want} contract never "
                f"passed (audited: {sorted(k[0] for k in audits)})"
            )
    # Prepared-tier engagement (PR 17): the auto planner must have
    # decided broadcast for the tiny build side and salted for the
    # heavy-hitter build side at least once across the walk (counters
    # never evict; the prepares above also assert the side objects).
    prepared_tiers = {
        dict(labels).get("tier")
        for labels, v in obs.counter_series(
            "dj_prepared_tier_total"
        ).items()
        if v > 0
    }
    for want_tier in ("broadcast", "salted"):
        if want_tier not in prepared_tiers:
            violations.append(
                f"prepared-tier planner armed but the {want_tier} "
                f"build tier never engaged "
                f"(tiers seen: {sorted(t for t in prepared_tiers if t)})"
            )
    # Measured-truth invariants (ISSUE 15): with DJ_OBS_TRUTH armed
    # for the whole walk, (a) every builder that compiled a fresh
    # module reported its XLA truth (counters, which never evict, not
    # the bounded ring), (b) every model/XLA reconciliation ratio is
    # finite and positive (the histogram only ever observes
    # forecast/peak with both > 0 — an empty histogram means the
    # forecast scope went dark), and (c) the armed measured-HBM gate
    # was a graceful no-op on this stat-less backend — proven by the
    # walk having reached this line with its outcome invariants intact.
    miss_builders = {
        dict(labels).get("builder")
        for labels, v in obs.counter_series("dj_build_cache_total").items()
        if dict(labels).get("result") == "miss" and v > 0
    }
    truth_builders = {
        dict(labels).get("builder")
        for labels, v in obs.counter_series("dj_xla_cost_total").items()
        if v > 0
    }
    untruthed = sorted(b for b in miss_builders if b not in truth_builders)
    if untruthed:
        violations.append(
            f"compiled builders without xla_cost truth: {untruthed}"
        )
    ratio_raw = obs.histogram_raw("dj_model_xla_ratio")
    if ratio_raw is None or ratio_raw[3] == 0:
        violations.append(
            "dj_model_xla_ratio never populated (forecast scope or "
            "truth extraction went dark under the walk)"
        )
    elif not (ratio_raw[2] > 0 and ratio_raw[2] < float("inf")):
        violations.append(
            f"model/xla ratios not finite-positive (sum={ratio_raw[2]})"
        )
    measured_rejects = int(obs.counter_value(
        "dj_serve_rejected_total", reason="measured_hbm"
    ))
    if measured_rejects:
        violations.append(
            f"measured-HBM gate fired {measured_rejects}x on a "
            f"backend without memory_stats — the no-op contract broke"
        )
    summary = {
        "metric": "chaos_soak",
        "sites": len(FAULT_WALK),
        "truth": {
            "builders_compiled": sorted(
                b for b in miss_builders if b is not None
            ),
            "xla_cost_events": int(obs.counter_value("dj_xla_cost_total")),
            "model_xla_ratios": 0 if ratio_raw is None else ratio_raw[3],
        },
        "hlo_audits": {
            f"{c}:{verd}": int(v) for (c, verd), v in sorted(audits.items())
        },
        "autotune": {
            dict(labels).get("action", "?"): int(v)
            for labels, v in obs.counter_series(
                "dj_autotune_total"
            ).items()
        },
        "queries": sum(tally.values()),
        "traces_complete": f"{traces_complete}/{len(all_qids)}",
        "outcomes": dict(sorted(tally.items())),
        "skew": sk,
        "plan_tiers_engaged": sorted(
            t for t in tiers_engaged if t is not None
        ),
        "prepared_tiers_engaged": sorted(
            t for t in prepared_tiers if t is not None
        ),
        "elapsed_s": round(time.perf_counter() - t_start, 2),
        "ok": not violations,
        "violations": violations,
    }
    print(json.dumps(summary))
    return 0 if not violations else 1


def hard_death_child() -> int:
    """The victim (module docstring): arm the black box from env,
    open real queries through a real scheduler, and die by SIGTERM
    with the queries still in flight. Anything printed after the
    kill — or a return — is a harness failure."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import signal

    import dj_tpu
    import dj_tpu.obs as obs
    from dj_tpu.core import table as T
    from dj_tpu.obs import forensics
    from dj_tpu.serve import QueryScheduler, ServeConfig

    armed = forensics.maybe_arm_from_env()
    assert armed, "child expected DJ_OBS_BLACKBOX in its environment"
    obs.enable()
    rng = np.random.default_rng(3)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    lk = rng.integers(0, 500, ROWS).astype(np.int64)
    rk = rng.integers(0, 500, ROWS).astype(np.int64)
    left, lc = dj_tpu.shard_table(
        topo, T.from_arrays(lk, np.arange(ROWS, dtype=np.int64))
    )
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(ROWS, dtype=np.int64))
    )
    cfg = dj_tpu.JoinConfig(bucket_factor=4.0, join_out_factor=4.0)
    sched = QueryScheduler(ServeConfig())
    # Several in-flight queries: submit opens each timeline's `query`
    # span; nobody ever awaits a result, so the spans are open when
    # the signal lands (the first may finish compiling+running on the
    # worker — the LATER ones are provably still queued/running).
    tickets = [
        sched.submit(topo, left, lc, right, rc, [0], [0], cfg)
        for _ in range(4)
    ]
    print(
        json.dumps({"child_qids": [t.query_id for t in tickets]}),
        flush=True,
    )
    # Die the way a preempted fleet worker dies. The forensics handler
    # dumps the bundle, restores the default disposition, and
    # re-raises — the exit code must still say "killed by SIGTERM".
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(TIMEOUT_S)  # never reached; the signal kills us
    return 3


def hard_death() -> int:
    """The auditor (module docstring): run the child, then assert the
    black-box contract on what it left behind."""
    import glob
    import subprocess
    import tempfile

    bb_dir = tempfile.mkdtemp(prefix="dj-soak-blackbox-")
    env = dict(os.environ)
    env["DJ_OBS_BLACKBOX"] = bb_dir
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--hard-death-child"],
        env=env, capture_output=True, text=True, timeout=TIMEOUT_S,
    )
    violations: list[str] = []
    # Died BY the signal: -15 from subprocess (or a 143 shell coat).
    if proc.returncode not in (-15, 143):
        violations.append(
            f"child exited {proc.returncode}, expected death by "
            f"SIGTERM (-15)"
        )
    for name, stream in (("stdout", proc.stdout), ("stderr", proc.stderr)):
        if "Traceback (most recent call last)" in stream:
            violations.append(
                f"bare traceback in child {name} — the death handlers "
                f"must dump, not splatter"
            )
    qids: list = []
    for line in proc.stdout.splitlines():
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        qids = obj.get("child_qids") or qids
    if not qids:
        violations.append("child never reported its query ids")
    bundles = glob.glob(os.path.join(bb_dir, "blackbox-*.jsonl"))
    sections: dict = {}
    if len(bundles) != 1:
        violations.append(
            f"expected exactly one bundle in {bb_dir}, found "
            f"{sorted(os.path.basename(b) for b in bundles)}"
        )
    else:
        with open(bundles[0]) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    sections[obj.pop("section")] = obj
                except (ValueError, KeyError):
                    violations.append("torn line in an UNtorn dump")
        # Completeness: every section the dump promises, parseable.
        for want in ("meta", "traces", "ring", "metrics", "knobs"):
            if want not in sections:
                violations.append(f"bundle missing section {want!r}")
        meta = sections.get("meta") or {}
        if meta.get("reason") != "sigterm":
            violations.append(
                f"bundle reason {meta.get('reason')!r}, expected "
                f"'sigterm'"
            )
        open_traces = (sections.get("traces") or {}).get("open") or []
        open_ids = {t.get("query_id") for t in open_traces}
        dead = [q for q in qids if q in open_ids]
        if not dead:
            violations.append(
                f"no submitted query ({qids}) has an OPEN timeline in "
                f"the bundle (open: {sorted(open_ids)})"
            )
        for tr in open_traces:
            if tr.get("complete"):
                violations.append(
                    f"open timeline {tr.get('query_id')} claims "
                    f"complete=true"
                )
            spans = tr.get("spans") or {}
            q = spans.get("query") or {}
            if not (q.get("begin", 0) > q.get("end", 0)):
                violations.append(
                    f"open timeline {tr.get('query_id')}: `query` "
                    f"span not marked open (spans={spans})"
                )
        ring = (sections.get("ring") or {}).get("events") or []
        if not any(
            e.get("type") == "blackbox" and e.get("reason") == "sigterm"
            for e in ring
        ):
            violations.append(
                "ring section lacks the dump's own blackbox event"
            )
    # The reader must reconstruct the story: exit 0, dead qid named.
    reader = subprocess.run(
        [
            sys.executable,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "blackbox_read.py"),
            bb_dir,
        ],
        capture_output=True, text=True, timeout=60,
    )
    if reader.returncode != 0:
        violations.append(
            f"blackbox_read.py exited {reader.returncode}: "
            f"{reader.stderr.strip()[:200]}"
        )
    elif qids and not any(q in reader.stdout for q in qids):
        violations.append(
            "blackbox_read.py output never names a dead query id"
        )
    summary = {
        "metric": "chaos_soak_hard_death",
        "child_exit": proc.returncode,
        "queries_in_flight": len(qids),
        "bundle_sections": sorted(sections),
        "open_timelines": len(
            (sections.get("traces") or {}).get("open") or []
        ),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "ok": not violations,
        "violations": violations,
    }
    print(json.dumps(summary))
    return 0 if not violations else 1


FLEET_TTL_S = 0.5


def _fleet_tables(rows: int, skew: bool = False):
    """Deterministic drill tables: every drill process must compute
    the IDENTICAL plan signature (lease keys and manifest records are
    matched across processes), so everything derives from one fixed
    seed. ``skew=True`` is the phase-3 shape — the build side is ONE
    hot key, so a small ``bucket_factor`` deterministically overflows
    its resident partition and the prepare HEALS to a larger settled
    factor (the learned plan the survivor must replay)."""
    import dj_tpu
    from dj_tpu.core import table as T

    rng = np.random.default_rng(11)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    lk = rng.integers(0, 200, rows).astype(np.int64)
    if skew:
        rk = np.full(rows, 7, dtype=np.int64)
        # Two anchor rows stretch the build side's probed key_range to
        # [0, 200] — covering every probe key, so the replayed side
        # serves WITHOUT a range-widening re-prepare and the survivor's
        # only manifest insert is the replay itself.
        rk[0] = 0
        rk[1] = 200
        lk[:4] = 7  # guaranteed matches against the hot build key
        # bucket_factor 4.0 is safe for the uniform PROBE side but the
        # one-key BUILD side lands every row on one partition, so the
        # prepare must heal it upward — the settled factor is the
        # learned plan phase 3 replays. join_out is wide because every
        # match shares that partition too.
        cfg = dj_tpu.JoinConfig(bucket_factor=4.0, join_out_factor=64.0)
    else:
        rk = rng.integers(0, 200, rows).astype(np.int64)
        cfg = dj_tpu.JoinConfig(bucket_factor=4.0, join_out_factor=4.0)
    left, lc = dj_tpu.shard_table(
        topo, T.from_arrays(lk, np.arange(rows, dtype=np.int64))
    )
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(rk, np.arange(rows, dtype=np.int64))
    )
    oracle = int(
        sum((lk == k).sum() * (rk == k).sum() for k in np.unique(rk))
    )
    return topo, left, lc, right, rc, cfg, oracle


def fleet_child(mode: str, rows: int) -> int:
    """A drill peer (``--fleet`` arm): computes the same deterministic
    tables/signature as the parent, then either holds the prepare
    lease and hangs (``hold`` — the parent SIGKILLs it mid-"build"),
    completes a real prepare and stays alive (``prepare-hold`` — the
    live owner the parent must defer to), or completes a prepare and
    exits (``prepare-exit`` — the dead owner whose settled plan the
    parent must replay)."""
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    from dj_tpu import fleet as coord
    from dj_tpu.cache import IndexConfig, JoinIndexCache
    from dj_tpu.parallel.dist_join import _config_factors
    from dj_tpu.resilience import ledger as dj_ledger

    assert coord.enabled(), "drill child expects DJ_FLEET_DIR in its env"
    topo, left, lc, right, rc, cfg, _ = _fleet_tables(
        rows, skew=(mode == "prepare-exit")
    )
    sig = dj_ledger.plan_signature(topo, None, right, None, (0,), cfg)
    if mode == "hold":
        lease = coord.leases.acquire(f"prepare|default||{sig}")
        assert lease is not None, "hold child lost the lease race to nobody"
        print(json.dumps({"phase": "holding", "pid": os.getpid()}), flush=True)
        time.sleep(TIMEOUT_S)  # SIGKILLed by the parent mid-"build"
        return 3
    idx = JoinIndexCache(IndexConfig(
        hbm_budget_bytes=500e6,
        manifest_path=os.path.join(
            os.environ["DJ_FLEET_DIR"], "manifest.jsonl"
        ),
    ))
    lease = idx.get_or_prepare(
        topo, right, rc, [0], cfg, left_capacity=left.capacity
    )
    factors = _config_factors(lease.prepared.config)
    lease.release()
    print(
        json.dumps(
            {"phase": "prepared", "pid": os.getpid(), "factors": factors}
        ),
        flush=True,
    )
    if mode == "prepare-hold":
        time.sleep(TIMEOUT_S)  # stays the LIVE owner until killed
    return 0


def fleet_drill() -> int:
    """The PR-20 coordination drill (module docstring): three phases
    against real subprocess peers sharing one ``DJ_FLEET_DIR`` —
    defer-to-live-owner, SIGKILL-mid-prepare lease reclaim, and
    dead-owner plan replay. Every parent query must reach a typed
    terminal; duplicate prepares must be zero."""
    import subprocess

    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    shared = tempfile.mkdtemp(prefix="dj-soak-fleet-")
    manifest = os.path.join(shared, "manifest.jsonl")
    os.environ["DJ_FLEET_DIR"] = shared
    os.environ["DJ_FLEET_LEASE_TTL_S"] = str(FLEET_TTL_S)
    os.environ["DJ_FLEET_LEASE_WAIT_S"] = "1.0"
    os.environ["DJ_LEDGER"] = os.path.join(shared, "ledger.jsonl")
    # The drill isolates the coordination layer; the adaptive /
    # autotune / bucketing layers ride the fault walk above.
    for k in ("DJ_PLAN_ADAPT", "DJ_AUTOTUNE", "DJ_PREPARED_TIER",
              "DJ_SHAPE_BUCKET", "DJ_HLO_AUDIT"):
        os.environ.pop(k, None)

    import dj_tpu.obs as obs
    from dj_tpu.cache import IndexConfig, JoinIndexCache
    from dj_tpu.resilience import ledger as dj_ledger
    from dj_tpu.resilience.errors import DJError
    from dj_tpu.serve import QueryScheduler, ServeConfig

    obs.enable()
    env = dict(os.environ)
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    violations: list[str] = []
    tally: dict[str, int] = {}
    phases: dict = {}
    children: list = []
    t0 = time.perf_counter()

    def spawn(mode: str, rows: int):
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--fleet-child", mode, str(rows)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        children.append(p)
        line = p.stdout.readline()
        try:
            return p, json.loads(line)
        except ValueError:
            err = p.stderr.read()[:400] if p.poll() is not None else "..."
            violations.append(
                f"{mode} child spoke {line!r} instead of JSON "
                f"(stderr: {err})"
            )
            return p, {}

    def run_query(sched, rows: int, skew: bool) -> None:
        topo, left, lc, right, rc, cfg, oracle = _fleet_tables(
            rows, skew=skew
        )
        try:
            t = sched.submit(topo, left, lc, right, rc, [0], [0], cfg)
        except DJError as e:
            tally[type(e).__name__] = tally.get(type(e).__name__, 0) + 1
            violations.append(
                f"rows={rows}: door shed {type(e).__name__} where a "
                f"result was expected: {e}"
            )
            return
        try:
            r = t.result(timeout=TIMEOUT_S)
        except TimeoutError:
            violations.append(f"HANG: drill query rows={rows}")
            return
        except DJError as e:
            tally[type(e).__name__] = tally.get(type(e).__name__, 0) + 1
            violations.append(
                f"rows={rows}: typed {type(e).__name__} where a "
                f"result was expected: {e}"
            )
            return
        except BaseException as e:  # noqa: BLE001
            violations.append(
                f"rows={rows}: BARE exception {type(e).__name__}: {e}"
            )
            return
        tally["result"] = tally.get("result", 0) + 1
        got = int(np.asarray(r[1]).sum())
        if got != oracle:
            violations.append(f"rows={rows}: wrong rows {got} != {oracle}")

    def manifest_inserts(sig: str) -> list:
        out = []
        try:
            with open(manifest) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail line
                    if rec.get("op") == "insert" and rec.get("sig") == sig:
                        out.append(rec)
        except OSError:
            pass
        return out

    def _sig(rows: int, skew: bool) -> str:
        topo, _, _, right, _, cfg, _ = _fleet_tables(rows, skew=skew)
        return dj_ledger.plan_signature(topo, None, right, None, (0,), cfg)

    idx = JoinIndexCache(
        IndexConfig(hbm_budget_bytes=500e6, manifest_path=manifest)
    )
    try:
        with QueryScheduler(
            ServeConfig(hbm_budget_bytes=500e6), index=idx
        ) as sched:
            # Phase 1 — fleet-wide prepare-once: a LIVE peer owns the
            # signature, so the parent's identical submit must DEFER
            # (serve unprepared, row-exact) instead of duplicating the
            # build.
            c1, msg = spawn("prepare-hold", 256)
            if msg.get("phase") != "prepared":
                violations.append(f"defer phase: child never prepared ({msg})")
            defer0 = int(obs.counter_value("dj_fleet_peer_defer_total"))
            prep0 = int(obs.counter_value(
                "dj_tenant_prepares_total", tenant="default"
            ))
            run_query(sched, 256, False)
            defers = int(
                obs.counter_value("dj_fleet_peer_defer_total")
            ) - defer0
            dup = int(obs.counter_value(
                "dj_tenant_prepares_total", tenant="default"
            )) - prep0
            if defers != 1:
                violations.append(
                    f"defer phase: expected exactly one peer defer, "
                    f"saw {defers}"
                )
            if dup != 0:
                violations.append(
                    f"defer phase: parent paid {dup} duplicate "
                    f"prepare(s) against a live owner"
                )
            if any(
                x.get("pid") == os.getpid()
                for x in manifest_inserts(_sig(256, False))
            ):
                violations.append(
                    "defer phase: parent wrote a duplicate insert record"
                )
            c1.kill()
            c1.wait()
            phases["defer"] = {"defers": defers, "duplicate_prepares": dup}

            # Phase 2 — SIGKILL mid-prepare: the dead peer holds the
            # lease; once its heartbeat crosses the TTL the survivor
            # must reclaim (exactly one winner) and build the side.
            c2, msg = spawn("hold", 320)
            if msg.get("phase") != "holding":
                violations.append(f"reclaim phase: child never held ({msg})")
            c2.kill()
            c2.wait()
            time.sleep(FLEET_TTL_S + 0.4)  # heartbeat crosses the TTL
            recl0 = int(obs.counter_value("dj_fleet_lease_reclaimed_total"))
            run_query(sched, 320, False)
            recl = int(
                obs.counter_value("dj_fleet_lease_reclaimed_total")
            ) - recl0
            if recl != 1:
                violations.append(
                    f"reclaim phase: expected exactly one lease "
                    f"reclaim, saw {recl}"
                )
            if len([
                x for x in manifest_inserts(_sig(320, False))
                if x.get("pid") == os.getpid()
            ]) != 1:
                violations.append(
                    "reclaim phase: survivor did not publish the "
                    "rebuilt side"
                )
            phases["reclaim"] = {"reclaims": recl}

            # Phase 3 — dead-owner replay: the peer settled a HEALED
            # plan into the shared manifest and died; the survivor
            # must replay those factors (zero prepare-stage heals),
            # not re-pay the ladder.
            c3, msg = spawn("prepare-exit", 64)
            rc3 = c3.wait(timeout=120)
            if rc3 != 0 or msg.get("phase") != "prepared":
                violations.append(
                    f"replay phase: dead-owner child failed "
                    f"(exit {rc3}, {msg})"
                )
            child_factors = msg.get("factors") or {}
            replay0 = int(obs.counter_value("dj_fleet_replay_total"))
            heal0 = len([
                e for e in obs.events("heal")
                if e.get("stage") == "prepare"
            ])
            run_query(sched, 64, True)
            replays = int(
                obs.counter_value("dj_fleet_replay_total")
            ) - replay0
            heals = len([
                e for e in obs.events("heal")
                if e.get("stage") == "prepare"
            ]) - heal0
            if replays != 1:
                violations.append(
                    f"replay phase: expected exactly one dead-owner "
                    f"replay, saw {replays}"
                )
            if heals != 0:
                violations.append(
                    f"replay phase: survivor re-healed the dead "
                    f"owner's plan ({heals} prepare heal(s)) instead "
                    f"of replaying it"
                )
            own3 = [
                x for x in manifest_inserts(_sig(64, True))
                if x.get("pid") == os.getpid()
            ]
            if len(own3) != 1:
                violations.append(
                    "replay phase: survivor did not publish the "
                    "replayed side"
                )
            else:
                got_f = own3[-1].get("factors") or {}
                if got_f != child_factors:
                    violations.append(
                        f"replay phase: survivor factors {got_f} != "
                        f"dead owner's settled {child_factors}"
                    )
                if float(child_factors.get("bucket_factor", 0.0)) <= 4.0:
                    violations.append(
                        "replay phase: the dead owner's plan never "
                        "actually healed — the replay assertion is "
                        "vacuous"
                    )
            phases["replay"] = {
                "replays": replays,
                "prepare_heals": heals,
                "factors": child_factors,
            }
    finally:
        for p in children:
            if p.poll() is None:
                p.kill()
                p.wait()

    summary = {
        "metric": "chaos_soak_fleet",
        "phases": phases,
        "queries": sum(tally.values()),
        "outcomes": dict(sorted(tally.items())),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "ok": not violations,
        "violations": violations,
    }
    print(json.dumps(summary))
    return 0 if not violations else 1


if __name__ == "__main__":
    if "--hard-death-child" in sys.argv:
        sys.exit(hard_death_child())
    if "--hard-death" in sys.argv or bool(
        os.environ.get("DJ_SOAK_HARD_DEATH")
    ):
        sys.exit(hard_death())
    if "--fleet-child" in sys.argv:
        i = sys.argv.index("--fleet-child")
        sys.exit(fleet_child(sys.argv[i + 1], int(sys.argv[i + 2])))
    if "--fleet" in sys.argv or bool(os.environ.get("DJ_SOAK_FLEET")):
        sys.exit(fleet_drill())
    sys.exit(main())
