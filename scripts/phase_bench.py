"""Phase microbenchmark: time every primitive in the bench.py hot path.

The distributed join pipeline is ONE jitted computation, so host-side
PhaseTimer cannot attribute time inside it. This script times each
constituent primitive at the exact shapes bench.py produces
(ROWS=100M, odf=4 => batch caps 32.5M, join out cap 19.5M) — the
measured phase breakdown VERDICT round-2 directive #1 demands. The
reference prints per-phase ms at every stage
(/root/reference/src/distributed_join.cpp:235-240, 316-321); this is
the equivalent attribution for the fused-XLA world.

Measurement method: the axon device tunnel adds ~40-100ms of variable
dispatch+sync overhead per host round-trip, so single-dispatch timing
is useless below ~1s. Each phase therefore runs K iterations inside
ONE jitted `lax.fori_loop` with a scalar feedback chain (prevents
loop-invariant hoisting and DCE), with K a *dynamic* argument so one
compilation serves both K=1 and K=1+REPS; the per-iteration cost is
the slope (t[K1] - t[1]) / REPS. The feedback adds one elementwise
pass over the first input per iteration (<1ms at these sizes).

Run on the real TPU:  python scripts/phase_bench.py
Scale down:           DJ_PHASE_ROWS=10000000 python scripts/phase_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("DJ_PHASE_ROWS", 100_000_000))
ODF = int(os.environ.get("DJ_PHASE_ODF", 4))
REPS = int(os.environ.get("DJ_PHASE_REPS", 8))

RESULTS: dict[str, float] = {}


def _sync(out):
    import jax

    for leaf in jax.tree.leaves(out):
        np.asarray(leaf)  # axon tunnel: block_until_ready doesn't sync


def timeit(name, body, *args):
    """body(*args) -> (args', feed_scalar_f32); times the slope per call.

    args' must match args in shape/dtype. feed must depend on the
    phase's output; the harness folds it back into args[0].
    """
    import jax
    import jax.numpy as jnp

    def looped(k, *args0):
        def step(_, carry):
            acc, args = carry
            new_args, feed = body(*args)
            new_args = list(new_args)
            # Feedback: fold the (data-dependent) scalar into input 0 so
            # the loop body can't be hoisted and nothing is dead.
            a0 = new_args[0]
            new_args[0] = a0 + (feed.astype(jnp.int32) & 1).astype(a0.dtype)
            return acc + feed, tuple(new_args)

        acc, _ = jax.lax.fori_loop(0, k, step, (jnp.float32(0), args0))
        return acc

    f = jax.jit(looped)
    t0 = time.perf_counter()
    _sync(f(1, *args))  # compile + warmup
    compile_s = time.perf_counter() - t0
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        _sync(f(1, *args))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync(f(1 + REPS, *args))
        tk = time.perf_counter() - t0
        per = (tk - t1) / REPS * 1e3
        best = per if best is None else min(best, per)
    RESULTS[name] = round(best, 2)
    print(f"{name:46s} {best:9.2f} ms   (compile {compile_s:5.1f} s)",
          flush=True)
    return best


def feed_of(x):
    """Cheap un-DCE-able scalar from an output array."""
    import jax.numpy as jnp

    return jnp.asarray(x).ravel()[0].astype(jnp.float32)


def main():
    import jax
    import jax.numpy as jnp

    from dj_tpu.core import table as T
    from dj_tpu.core.search import count_leq_arange, rank_in_sorted
    from dj_tpu.ops.join import inner_join
    from dj_tpu.ops.partition import hash_partition, partition_counts_from_ids

    n = 1  # single chip
    m = n * ODF
    bl = max(1, int(ROWS * 1.3 / m))          # batch bucket rows
    out_cap = max(1, int(0.6 * n * bl))       # join out capacity
    merged = 2 * bl

    print(f"ROWS={ROWS:,} odf={ODF} batch_cap={bl:,} out_cap={out_cap:,} "
          f"reps={REPS}", flush=True)

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)

    keys100 = jax.random.randint(k1, (ROWS,), 0, 2 * ROWS, dtype=jnp.int64)
    pay100 = jnp.arange(ROWS, dtype=jnp.int64)
    pid100 = jax.random.randint(k2, (ROWS,), 0, m, dtype=jnp.int32)
    keys_b = jax.random.randint(k3, (bl,), 0, 2 * ROWS, dtype=jnp.int64)
    pay_b = jnp.arange(bl, dtype=jnp.int64)
    idx_out = jax.random.randint(k4, (out_cap,), 0, bl, dtype=jnp.int32)
    vals_m = jax.random.randint(k1, (merged,), 0, 2 * ROWS, dtype=jnp.int64)
    tag_m = jax.random.randint(k2, (merged,), 0, merged, dtype=jnp.int32)
    hist_vals = jnp.sort(
        jax.random.randint(k3, (merged,), 0, out_cap, dtype=jnp.int64)
    )
    _sync((keys100, pay100, pid100, keys_b, pay_b, idx_out, vals_m, tag_m,
           hist_vals))

    # --- dispatch overhead reference ----------------------------------
    timeit(
        "noop (dispatch overhead floor)",
        lambda x: ((x,), feed_of(x[:1] + 1)),
        jnp.arange(8, dtype=jnp.int32),
    )

    # --- primitive phases ---------------------------------------------
    def sort_partition(p, a, b):
        sp, sa, sb = jax.lax.sort((p % jnp.int32(m), a, b), num_keys=1,
                                  is_stable=True)
        return (sp, sa, sb), feed_of(sa)

    timeit("sort[pid_i32 + 2xi64] @ROWS (partition)",
           sort_partition, pid100, keys100, pay100)

    def sort_partition_sbk(p, a, b):
        sp, sa, sb = jax.lax.sort((p % jnp.int32(m), a, b), num_keys=2,
                                  is_stable=True)
        return (sp, sa, sb), feed_of(sb)

    timeit("sort[pid,key 2keys + i64] @ROWS (part+sbk)",
           sort_partition_sbk, pid100, keys100, pay100)

    def sort_pair(a, b):
        sa, sb = jax.lax.sort((a, b), num_keys=1, is_stable=True)
        return (sa, sb), feed_of(sb)

    timeit("sort[i64 + i64] @batch (right sort)", sort_pair, keys_b, pay_b)

    def sort_merge(a, t):
        sa, st = jax.lax.sort((a, t), num_keys=1, is_stable=True)
        return (sa, st), feed_of(st)

    timeit("sort[i64 + i32tag] @2xbatch (match merge)",
           sort_merge, vals_m, tag_m)

    def sort_packed(a):
        sa = jax.lax.sort(a)
        return (sa,), feed_of(sa)

    timeit("sort[u64 packed] @2xbatch (match merge packed)",
           sort_packed, vals_m.astype(jnp.uint64))

    def sort_merge_carry(a, t, p):
        sa, st, sp = jax.lax.sort((a, t, p), num_keys=1, is_stable=True)
        return (sa, st, sp), feed_of(sp)

    timeit("sort[i64 + i32 + u64pay] @2xbatch (carry)",
           sort_merge_carry, vals_m, tag_m, vals_m.astype(jnp.uint64))

    def scat_set(t):
        out = jnp.zeros((bl,), jnp.int32).at[t].set(t, mode="drop")
        return (t,), feed_of(out)

    timeit("scatter_set_i32 @2xbatch->batch (removed r2)", scat_set, tag_m)

    def hist_leq(v):
        out = count_leq_arange(v, out_cap)
        return (v,), feed_of(out)

    timeit("count_leq_arange @2xbatch->out (expansion)", hist_leq, hist_vals)

    def ris_expand(v):
        out = rank_in_sorted(v, jnp.arange(out_cap, dtype=v.dtype), "right")
        return (v,), feed_of(out)

    timeit("rank_in_sorted alt @2xbatch->out (expansion)", ris_expand,
           hist_vals)

    from dj_tpu.ops.pallas_expand import expand_ranks

    def pallas_expand(v):
        out = expand_ranks(v, out_cap)
        return (v,), feed_of(out)

    timeit("pallas expand_ranks @2xbatch->out (expansion)", pallas_expand,
           hist_vals)

    def hist_m(p):
        out = jnp.zeros((m,), jnp.int32).at[p % jnp.int32(m)].add(
            1, mode="drop")
        return (p,), feed_of(out)

    timeit("scatter_add hist @ROWS->m buckets (old)", hist_m, pid100)

    def hist_onehot(p):
        out = partition_counts_from_ids(p % jnp.int32(m), m)
        return (p,), feed_of(out)

    timeit("one-hot hist @ROWS->m buckets (offsets)", hist_onehot, pid100)

    pack2m = jnp.stack([vals_m.astype(jnp.uint64)] * 2, axis=-1)
    pack2b = jnp.stack([keys_b.astype(jnp.uint64)] * 2, axis=-1)
    idx_out_m = jax.random.randint(
        k4, (out_cap,), 0, merged, dtype=jnp.int32
    )
    _sync((pack2m, pack2b, idx_out_m))

    def gather2m(d, i):
        out = d.at[i].get(mode="fill", fill_value=0)
        return (d, i), feed_of(out)

    timeit("gather [2xbatch,2]u64 @out rows (meta)", gather2m, pack2m,
           idx_out_m)

    pack4m = jnp.stack([vals_m.astype(jnp.uint64)] * 4, axis=-1)
    _sync(pack4m)
    timeit("gather [2xbatch,4]u64 @out rows (carry)", gather2m, pack4m,
           idx_out_m)

    timeit("gather flat u64 @out rows (width ref)", gather2m,
           vals_m.astype(jnp.uint64), idx_out_m)

    timeit("gather [batch,2]u64 @out rows (tbl rows)", gather2m, pack2b,
           idx_out)

    def gather1(d, i):
        out = d.at[i].get(mode="fill", fill_value=0)
        return (d, i), feed_of(out)

    timeit("gather flat i32 @out rows (rtag)", gather1,
           tag_m, idx_out_m)

    def cs64(v):
        out = jnp.cumsum(v)
        return (v,), feed_of(out)

    timeit("cumsum_i64 @batch", cs64, pay_b)

    def cs32(t):
        out = jnp.cumsum(t)
        return (t,), feed_of(out)

    timeit("cumsum_i32 @2xbatch", cs32, tag_m)

    def cm32(t):
        out = jax.lax.cummax(t)
        return (t,), feed_of(out)

    timeit("cummax_i32 @2xbatch", cm32, tag_m)
    timeit("cummax_i64 @2xbatch (packed runs)", cm32, vals_m)

    def shuffle1(a, b):
        oa = jax.lax.dynamic_slice_in_dim(jnp.pad(a, (0, bl)), 0, bl)
        ob = jax.lax.dynamic_slice_in_dim(jnp.pad(b, (0, bl)), 0, bl)
        return (a, b), feed_of(oa) + feed_of(ob)

    timeit("pad+dyn_slice 2cols @ROWS->batch (shuffle1)",
           shuffle1, keys100, pay100)

    # --- composite phases ---------------------------------------------
    def part_full(a, b):
        t = T.from_arrays(a, b)
        out, off = hash_partition(t, [0], m, seed=12345678)
        return (a, b), feed_of(out.columns[0].data) + feed_of(off)

    timeit("hash_partition @ROWS m=odf (full)", part_full, keys100, pay100)

    rkeys_b = jax.random.randint(k2, (bl,), 0, 2 * ROWS, dtype=jnp.int64)
    _sync(rkeys_b)

    # Fused two-table batch epoch at the production shapes (n=1: the
    # degenerate self-copy path, so this times the data movement of
    # the fused shuffle_tables wiring dist_join now uses per batch —
    # both tables in one call — without collective dispatch).
    from dj_tpu.parallel.all_to_all import shuffle_tables
    from dj_tpu.parallel.communicator import XlaCommunicator
    from dj_tpu.parallel.topology import CommunicationGroup

    comm1 = XlaCommunicator(CommunicationGroup("world", 1))
    z1 = jnp.zeros((1,), jnp.int32)
    cnt_b = jnp.full((1,), bl, jnp.int32)

    def shuffle_pair_fused(lk, lp, rk, rp):
        lt = T.from_arrays(lk, lp)
        rt = T.from_arrays(rk, rp)
        (lo, _, _, _), (ro, _, _, _) = shuffle_tables(
            comm1, [lt, rt], [z1, z1], [cnt_b, cnt_b], [bl, bl], [bl, bl]
        )
        return (lk, lp, rk, rp), (
            feed_of(lo.columns[0].data) + feed_of(ro.columns[0].data)
        )

    timeit("shuffle_tables 2tbl fused @batch (dist_join)",
           shuffle_pair_fused, keys_b, pay_b, rkeys_b, pay_b)

    def join_full(lk, lp, rk, rp):
        lt = T.from_arrays(lk, lp)
        rt = T.from_arrays(rk, rp)
        out, total = inner_join(lt, rt, [0], [0], out_capacity=out_cap)
        return (lk, lp, rk, rp), (
            feed_of(out.columns[0].data) + total.astype(jnp.float32)
        )

    timeit("inner_join @batch out_cap (full)", join_full,
           keys_b, pay_b, rkeys_b, pay_b)

    print(json.dumps({"rows": ROWS, "odf": ODF, "phases_ms": RESULTS}),
          flush=True)


if __name__ == "__main__":
    main()
