"""Comm/compute overlap study (VERDICT r2 directive #4).

The reference overlaps batch i's all-to-all with batch i-1's join via a
dedicated join thread + atomic flags
(/root/reference/src/distributed_join.cpp:247-329). This framework
claims XLA's async collectives give the same overlap inside one traced
computation (dist_join.py module docstring). This script tests that
claim two ways:

--mode sweep   (real TPU, 1 chip): wall-clock the headline pipeline at
               odf in {1,2,4,8}. With one chip there are NO collectives
               (degenerate self-copy shuffle), so this isolates what
               odf costs/buys in pure compute: smaller per-batch sorts
               (superlinear win) vs per-batch fixed overhead.
--mode hlo     (8-device CPU mesh): compile the full distributed join
               and inspect the optimized HLO for async collective pairs
               (all-to-all-start/-done or async-start/-done wrapping
               all-to-all) with compute scheduled between start and
               done — the compiler-level evidence of overlap the
               reference gets from its thread structure.

Results are committed to ARCHITECTURE.md's overlap section.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mode_sweep(rows: int, odfs):
    import jax

    import dj_tpu
    from dj_tpu import native
    from dj_tpu.core import table as T

    native.build()
    build_keys, probe_keys = native.generate_build_probe(
        rows, rows, 0.3, rows * 2, unique_build=True, seed=42
    )
    topo = dj_tpu.make_topology(devices=jax.devices()[:1])
    probe, pc = dj_tpu.shard_table(
        topo, T.from_arrays(probe_keys, np.arange(rows, dtype=np.int64))
    )
    build, bc = dj_tpu.shard_table(
        topo, T.from_arrays(build_keys, np.arange(rows, dtype=np.int64))
    )
    for odf in odfs:
        config = dj_tpu.JoinConfig(
            over_decom_factor=odf,
            bucket_factor=float(os.environ.get("DJ_BENCH_BUCKET", 1.1)),
            join_out_factor=float(os.environ.get("DJ_BENCH_JOF", 0.45)),
        )

        def run():
            out, counts, info = dj_tpu.distributed_inner_join(
                topo, probe, pc, build, bc, [0], [0], config
            )
            return np.asarray(counts), info

        counts, info = run()  # compile + warmup
        for k, v in info.items():
            assert not np.asarray(v).any(), f"odf={odf} {k}"
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            counts, _ = run()
            times.append(time.perf_counter() - t0)
        print(
            json.dumps(
                {
                    "mode": "sweep",
                    "rows": rows,
                    "odf": odf,
                    "elapsed_s": round(min(times), 4),
                    "matches": int(counts.sum()),
                }
            ),
            flush=True,
        )


def mode_hlo(rows: int, odf: int):
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    import dj_tpu
    from dj_tpu.core import table as T
    from dj_tpu.data.generator import host_build_probe_keys
    from dj_tpu.parallel.dist_join import _build_join_fn

    rng = np.random.default_rng(0)
    build_k, probe_k = host_build_probe_keys(rows, rows, 0.3, rng)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    probe, pc = dj_tpu.shard_table(
        topo, T.from_arrays(probe_k, np.arange(rows, dtype=np.int64))
    )
    build, bc = dj_tpu.shard_table(
        topo, T.from_arrays(build_k, np.arange(rows, dtype=np.int64))
    )
    config = dj_tpu.JoinConfig(
        over_decom_factor=odf, bucket_factor=2.0, join_out_factor=1.0
    )
    w = topo.world_size
    run = _build_join_fn(
        topo, config, (0,), (0,),
        probe.capacity // w, build.capacity // w,
    )
    compiled = run.lower(probe, pc, build, bc).compile()
    hlo = compiled.as_text()

    # Async collective pairs return tuple shapes (spaces before the op
    # name), so capture the result name at line start and look for the
    # op mnemonic anywhere after '='.
    lines = hlo.splitlines()
    starts = 0
    dones = 0
    sync_a2a = 0
    gaps = []
    open_at = {}
    for i, ln in enumerate(lines):
        name_m = re.match(r"\s*(?:ROOT\s+)?%?([\w.-]+) = ", ln)
        rhs = ln.split(" = ", 1)[1] if " = " in ln else ""
        if re.search(r"\ball-to-all-start\(", rhs) or (
            re.search(r"\basync-start", rhs) and "all-to-all" in rhs
        ):
            starts += 1
            if name_m:
                open_at[name_m.group(1)] = i
        elif re.search(r"\b(?:all-to-all-done|async-done)\(", rhs):
            dones += 1
            arg = re.search(r"\((?:[\w\[\]{},/* ]*%)?([\w.-]+)", rhs)
            if arg and arg.group(1) in open_at:
                gaps.append(i - open_at.pop(arg.group(1)) - 1)
        elif re.search(r"\ball-to-all\(", rhs):
            sync_a2a += 1
    print(
        json.dumps(
            {
                "mode": "hlo",
                "backend": jax.default_backend(),
                "odf": odf,
                "async_starts": starts,
                "async_dones": dones,
                "sync_all_to_alls": sync_a2a,
                "instrs_between_start_done": gaps,
                "note": (
                    "CPU XLA lowers all-to-all synchronously; async "
                    "pairs (and thus compiler-scheduled overlap) are a "
                    "TPU-backend feature — this mode documents the "
                    "collective structure, the TPU answer needs a "
                    "TPU-target compile"
                    if starts == 0
                    else "async pairs present; gaps>0 mean compute is "
                    "scheduled between start and done"
                ),
            }
        ),
        flush=True,
    )
    out = os.environ.get("DJ_HLO_OUT")
    if out:
        with open(out, "w") as f:
            f.write(hlo)
        print(f"wrote HLO to {out}", file=sys.stderr)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["sweep", "hlo"], required=True)
    p.add_argument("--rows", type=int, default=10_000_000)
    p.add_argument("--odf", type=int, default=4)
    p.add_argument("--odfs", type=str, default="1,2,4,8")
    a = p.parse_args()
    if a.mode == "sweep":
        mode_sweep(a.rows, [int(x) for x in a.odfs.split(",")])
    else:
        mode_hlo(a.rows, a.odf)
