"""CPU-mesh collective-path timing: trend-only shuffle regression guard.

All hardware perf data comes from ONE real chip, where the shuffle takes
the degenerate single-peer path — the actual collective path has zero
perf characterization (VERDICT r2 directive #8). This times a 1M-row
distributed join on the virtual 8-device CPU mesh: absolute numbers are
meaningless (host CPU), but a step change between revisions flags a
collective-path regression the 1-chip bench can't see.

Prints ONE JSON line; ci/bench_log.sh appends it to BENCH_LOG.jsonl.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("DJ_CPU_BENCH_ROWS", 1_000_000))


def setup(rows: int):
    """Shared CPU-mesh join harness: sharded tables + oracle count.

    Returns (topo, left, lc, right, rc, oracle). Also used by
    comm_bench.py so the two trend benches cannot drift.
    """
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8; "
        f"got {jax.devices()}"
    )
    import dj_tpu
    from dj_tpu.core import table as T
    from dj_tpu.data.generator import host_build_probe_keys

    rng = np.random.default_rng(0)
    build, probe = host_build_probe_keys(rows, rows, 0.3, rng)
    topo = dj_tpu.make_topology(devices=jax.devices()[:8])
    left, lc = dj_tpu.shard_table(
        topo, T.from_arrays(probe, np.arange(rows, dtype=np.int64))
    )
    right, rc = dj_tpu.shard_table(
        topo, T.from_arrays(build, np.arange(rows, dtype=np.int64))
    )
    oracle = int(np.isin(probe, build).sum())
    return topo, left, lc, right, rc, oracle


def timed_join(topo, left, lc, right, rc, oracle, config, iters: int = 1):
    """Compile+warmup (with overflow/oracle asserts), then best-of-iters
    wall clock of one distributed_inner_join call."""
    import dj_tpu

    def run():
        out, counts, info = dj_tpu.distributed_inner_join(
            topo, left, lc, right, rc, [0], [0], config
        )
        return np.asarray(counts), info

    counts, info = run()  # compile + warmup
    for k, v in info.items():
        assert not np.asarray(v).any(), f"{k} overflow"
    assert int(counts.sum()) == oracle
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def prepared_ab(harness, iters: int):
    """Prepared-vs-independent A/B on the real collective path: 4
    queries (distinct left tables) against ONE prepared right side vs
    4 independent unprepared joins. Absolute numbers are host-CPU
    noise; the RATIO is the end-to-end evidence that the prepared
    query path's halved exchange + amortized build-side work buys
    wall-clock (the 1-chip bench can't see it — its shuffle is the
    degenerate self-copy). Logged alongside the communicator
    backend-comparison entries (comm_bench.py) in BENCH_LOG.jsonl.

    Also emits a SECOND line, ``cpu_mesh_prepared_probe_ab_1m_8dev``:
    probe-vs-xla merge tier A/B at the SERVING SHAPE — 4 small query
    tables (rows/32 each) against the full-size resident side, served
    under DJ_JOIN_MERGE=probe (zero-sort binary-search tier,
    ops.join.inner_join_probe) vs the default concat-sort tier, value
    = probe/xla per-query ratio (< 1.0 = probe wins; bench_trend.py
    regression-guards it like every other entry). The small-query
    shape is the point, not a dodge: the probe tier's economics are
    2*log2(R) gathers of bl rows vs a (bl+br)-sized sort, so it wins
    when query batches are small relative to the resident run — the
    steady-state serving shape the prepared path exists for — and
    loses at symmetric batch sizes where the sort's cache-friendly
    passes beat per-row gather latency (Balkesen et al., VLDB 2013;
    the symmetric crossover rides scripts/hw/merge_crossover.py)."""
    import time as _t

    import dj_tpu
    from dj_tpu.core import table as T

    topo, left, lc, right, rc, oracle = harness
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=1.5, join_out_factor=0.8
    )
    rows = ROWS
    rng = np.random.default_rng(1)
    lefts = []
    for q in range(4):
        probe = rng.integers(0, 2 * rows, rows).astype(np.int64)
        lt, lcq = dj_tpu.shard_table(
            topo, T.from_arrays(probe, np.arange(rows, dtype=np.int64))
        )
        lefts.append((lt, lcq))

    def independent():
        totals = []
        for lt, lcq in lefts:
            _, counts, info = dj_tpu.distributed_inner_join(
                topo, lt, lcq, right, rc, [0], [0], config
            )
            totals.append(np.asarray(counts).sum())
        return totals

    def prepared_serve(prep):
        totals = []
        for lt, lcq in lefts:
            _, counts, info = dj_tpu.distributed_inner_join(
                topo, lt, lcq, prep, None, [0], None, config
            )
            for k, v in info.items():
                assert not np.asarray(v).any(), k
            totals.append(np.asarray(counts).sum())
        return totals

    # Warmup both pipelines (compiles), assert identical totals.
    prep = dj_tpu.prepare_join_side(
        topo, right, rc, [0], config, left_capacity=left.capacity
    )
    ti = independent()
    tp = prepared_serve(prep)
    assert [int(x) for x in ti] == [int(x) for x in tp], (ti, tp)

    best_i = best_p = best_prep = None
    for _ in range(iters):
        t0 = _t.perf_counter()
        independent()
        di = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        prep2 = dj_tpu.prepare_join_side(
            topo, right, rc, [0], config, left_capacity=left.capacity
        )
        dprep = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        prepared_serve(prep2)
        dp = _t.perf_counter() - t0
        best_i = di if best_i is None else min(best_i, di)
        best_p = dp if best_p is None else min(best_p, dp)
        best_prep = dprep if best_prep is None else min(best_prep, dprep)
    print(
        json.dumps(
            {
                "metric": "cpu_mesh_prepared_ab_1m_8dev",
                "value": round((best_p / 4) / (best_i / 4), 4),
                "unit": "prepared/independent per-query ratio "
                        "(CPU trend only)",
                "independent_per_query_s": round(best_i / 4, 4),
                "prepared_per_query_s": round(best_p / 4, 4),
                "prep_s": round(best_prep, 4),
            }
        ),
        flush=True,
    )

    # Probe-tier leg at the serving shape (docstring above): small
    # query tables vs the full resident side, BOTH tiers timed on that
    # same workload. The env knob folds into the query builder's cache
    # key (dist_join _env_key), so each flip retraces — warm once per
    # tier, then time.
    q_rows = max(8, rows // 32)
    small = []
    for q in range(4):
        probe_keys = rng.integers(0, 2 * rows, q_rows).astype(np.int64)
        lt, lcq = dj_tpu.shard_table(
            topo, T.from_arrays(
                probe_keys, np.arange(q_rows, dtype=np.int64)
            )
        )
        small.append((lt, lcq))
    # The prepared tag field is sized by left_capacity: a dedicated
    # prepare for the small-query shape (paid once, off the clock).
    prep_small = dj_tpu.prepare_join_side(
        topo, right, rc, [0], config, left_capacity=q_rows
    )

    def serve_small():
        totals = []
        for lt, lcq in small:
            _, counts, info = dj_tpu.distributed_inner_join(
                topo, lt, lcq, prep_small, None, [0], None, config
            )
            for k, v in info.items():
                assert not np.asarray(v).any(), k
            totals.append(int(np.asarray(counts).sum()))
        return totals

    prev = os.environ.get("DJ_JOIN_MERGE")
    tier_best = {}
    tier_totals = {}
    try:
        for tier in ("xla", "probe"):
            os.environ["DJ_JOIN_MERGE"] = tier
            tier_totals[tier] = serve_small()  # warmup/compile + flags
            best = None
            for _ in range(iters):
                t0 = _t.perf_counter()
                serve_small()
                dt = _t.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            tier_best[tier] = best
    finally:
        if prev is None:
            os.environ.pop("DJ_JOIN_MERGE", None)
        else:
            os.environ["DJ_JOIN_MERGE"] = prev
    assert tier_totals["probe"] == tier_totals["xla"], tier_totals
    print(
        json.dumps(
            {
                "metric": "cpu_mesh_prepared_probe_ab_1m_8dev",
                "value": round(
                    (tier_best["probe"] / 4) / (tier_best["xla"] / 4), 4
                ),
                "unit": "probe/xla prepared per-query ratio at the "
                        "serving shape (CPU trend only; < 1.0 = probe "
                        "tier wins)",
                "probe_per_query_s": round(tier_best["probe"] / 4, 4),
                "xla_per_query_s": round(tier_best["xla"] / 4, 4),
                "query_rows": q_rows,
                "resident_rows": rows,
            }
        ),
        flush=True,
    )


def _write_metrics():
    """DJ_BENCH_METRICS=path: dump the obs registry+ring snapshot
    (obs.write_snapshot owns the format) — the CPU-mesh twin of
    bench.py --metrics-out; ci/bench_log.sh embeds it next to the
    BENCH_LOG entry. Never fatal: a broken diagnostics sink must not
    fail the trend guard."""
    path = os.environ.get("DJ_BENCH_METRICS")
    if not path:
        return
    try:
        import dj_tpu.obs as obs

        obs.write_snapshot(path)
    except Exception as e:  # noqa: BLE001
        print(f"# metrics dump failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


def main():
    import dj_tpu
    import dj_tpu.obs as obs

    # Host-side only (HLO-equality guarded), so enabling it cannot
    # perturb the compiled modules this trend bench times.
    obs.enable()
    harness = setup(ROWS)
    if os.environ.get("DJ_CPU_BENCH_PREPARED_AB"):
        prepared_ab(
            harness, int(os.environ.get("DJ_CPU_BENCH_ITERS", 3))
        )
        return
    if os.environ.get("DJ_CPU_BENCH_ODF_AB"):
        # Over-decomposition A/B on the REAL collective path (8 CPU
        # devices): odf=1 issues one monolithic all-to-all per table;
        # odf=4 pipelines four batch shuffles against four local joins.
        # Absolute times are host-CPU noise, but the RATIO is the only
        # measured end-to-end evidence anywhere that the batched
        # pipeline shape doesn't cost wall-clock vs the monolithic
        # shuffle (the reference's signature optimization,
        # /root/reference/src/distributed_join.cpp:247-329; single-chip
        # TPU can't see it — the shuffle degenerates to a self-copy).
        iters = int(os.environ.get("DJ_CPU_BENCH_ITERS", 3))
        t1 = timed_join(
            *harness,
            dj_tpu.JoinConfig(
                over_decom_factor=1, bucket_factor=1.5, join_out_factor=0.8
            ),
            iters=iters,
        )
        t4 = timed_join(
            *harness,
            dj_tpu.JoinConfig(
                over_decom_factor=4, bucket_factor=1.5, join_out_factor=0.8
            ),
            iters=iters,
        )
        print(
            json.dumps(
                {
                    "metric": "cpu_mesh_odf_ab_1m_8dev",
                    "value": round(t4 / t1, 4),
                    "unit": "odf4/odf1 elapsed ratio (CPU trend only)",
                    "odf1_s": round(t1, 4),
                    "odf4_s": round(t4, 4),
                }
            )
        )
        return
    config = dj_tpu.JoinConfig(
        over_decom_factor=2, bucket_factor=1.5, join_out_factor=0.8
    )
    elapsed = timed_join(*harness, config)
    print(
        json.dumps(
            {
                "metric": "cpu_mesh_dist_join_1m_8dev_elapsed",
                "value": round(elapsed, 4),
                "unit": "s (CPU trend only, not TPU perf)",
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    finally:
        _write_metrics()
