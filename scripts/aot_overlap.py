"""AOT TPU overlap evidence: does XLA overlap the batched all-to-alls
with join compute on a REAL TPU target, with no TPU attached?

The reference overlaps batch i's communication with batch i-1's join via
a dedicated thread + atomic flags (/root/reference/src/
distributed_join.cpp:247-329). dj_tpu's design claim (dist_join.py
docstring) is that tracing the whole batched loop into one XLA
computation lets the compiler's async collectives + latency-hiding
scheduler do the same without host threads. The CPU-mesh study
(overlap_study.py) honestly showed CPU collectives lower synchronously,
so the claim was unverifiable off-chip — UNTIL noticing the local
libtpu can AOT-compile for a v5e topology (jax.experimental.topologies)
without any device. This script compiles the 8-device distributed join
exactly as production builds it (_build_join_fn) for v5e:2x4 and
inspects the optimized HLO schedule:

- counts async collective pairs (all-to-all-start/-done etc.);
- for each pair, counts the non-trivial compute ops scheduled BETWEEN
  start and done in the entry computation's schedule — sort/fusion ops
  between a batch's collective start and done ARE the overlap.

Run: scripts/hw/run_aot_overlap.sh (strips the axon env; needs
TPU_WORKER_HOSTNAMES=localhost for the compile-only libtpu client).
Output: JSON summary on stdout; full HLO to /tmp/aot_join_hlo.txt.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from jax.experimental import topologies
from jax.sharding import NamedSharding

import dj_tpu
from dj_tpu.core.table import Column, Table
from dj_tpu.parallel.dist_join import _build_join_fn, _env_key

ODF = int(os.environ.get("DJ_AOT_ODF", 4))
ROWS_PER_DEV = int(os.environ.get("DJ_AOT_ROWS", 262_144))
INTRA = os.environ.get("DJ_AOT_INTRA")  # e.g. 4 for two-level


def build():
    topo_desc = topologies.get_topology_desc("v5e:2x4", "tpu")
    devs = list(topo_desc.devices)
    topology = dj_tpu.make_topology(
        devices=devs, intra_size=int(INTRA) if INTRA else None
    )
    n = len(devs)
    rows = ROWS_PER_DEV * n
    config = dj_tpu.JoinConfig(
        over_decom_factor=ODF, bucket_factor=2.0, join_out_factor=1.0
    )
    fn = _build_join_fn(
        topology, config, (0,), (0,), ROWS_PER_DEV, ROWS_PER_DEV, _env_key()
    )
    sh = topology.row_sharding()
    i64 = jax.ShapeDtypeStruct((rows,), jnp.int64, sharding=sh)
    cnt = jax.ShapeDtypeStruct(
        (n,), jnp.int32, sharding=NamedSharding(topology.mesh, topology.row_spec())
    )
    tbl = Table((Column(i64, dj_tpu.dtypes.int64),
                 Column(i64, dj_tpu.dtypes.int64)))
    # Async all-to-all is a TPU backend flag (sync by default on this
    # XLA version); DJ_AOT_ASYNC=0 compiles the default for contrast.
    opts = (
        {"xla_tpu_enable_async_all_to_all": "true"}
        if os.environ.get("DJ_AOT_ASYNC", "1") == "1"
        else {}
    )
    return fn.lower(tbl, cnt, tbl, cnt).compile(compiler_options=opts)


_START_RE = re.compile(
    r"%((all-to-all|collective-permute|all-gather|all-reduce)"
    r"-start\.?\d*)\s*="
)
_DONE_RE = re.compile(r"-done\.?\d*\s*=.*-done\(%(\S+?-start\.?\d*)\)")
_CYCLES_RE = re.compile(r'"estimated_cycles":"(\d+)"')
_SHAPE_BYTES = {"s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
                "f32": 4, "s64": 8, "u64": 8, "f64": 8, "pred": 1, "bf16": 2}


def _op_bytes(line: str) -> int:
    """Rough payload bytes of the op's result shape(s) on one line."""
    total = 0
    for m in re.finditer(r"\b(pred|[suf]\d+|bf16)\[([\d,]*)\]", line):
        dims = [int(d) for d in m.group(2).split(",") if d]
        n = 1
        for d in dims:
            n *= d
        total += n * _SHAPE_BYTES.get(m.group(1), 4)
    return total


def analyze(hlo: str) -> dict:
    """Scan the SCHEDULED entry computation (is_scheduled=true: line
    order == schedule order): for every async collective start/done
    pair, count the compute ops and their cost-model cycles scheduled
    inside the window — that is exactly the comm/compute overlap the
    reference builds by hand with a join thread."""
    lines = hlo.splitlines()
    pairs = []
    open_pairs: dict[str, int] = {}
    counts = {"all-to-all": 0, "collective-permute": 0, "all-gather": 0,
              "all-reduce": 0}
    compute_re = re.compile(r"= \S+ (fusion|sort|scatter|gather|reduce|"
                            r"select-and-scatter|convolution|dot)\(")
    for i, ln in enumerate(lines):
        m = _START_RE.search(ln)
        if m:
            open_pairs[m.group(1)] = i
            counts[m.group(2)] += 1
            continue
        d = _DONE_RE.search(ln)
        if d and d.group(1) in open_pairs:
            s = open_pairs.pop(d.group(1))
            ops = cyc = 0
            for j in range(s + 1, i):
                if compute_re.search(lines[j]):
                    ops += 1
                    c = _CYCLES_RE.search(lines[j])
                    if c:
                        cyc += int(c.group(1))
            pairs.append({
                "start_line": s + 1,
                "done_line": i + 1,
                "window_lines": i - s - 1,
                "payload_bytes": _op_bytes(lines[s]),
                "compute_ops_between": ops,
                "compute_cycles_between": cyc,
            })
    data_pairs = [p for p in pairs if p["payload_bytes"] >= 1 << 16]
    return {
        "async_pairs": len(pairs),
        "async_starts_by_kind": counts,
        "pairs_with_compute_between": sum(
            1 for p in pairs if p["compute_ops_between"] > 0
        ),
        "data_pairs": len(data_pairs),
        "data_pairs_overlapped": sum(
            1 for p in data_pairs if p["compute_ops_between"] > 0
        ),
        "total_compute_cycles_inside_async_windows": sum(
            p["compute_cycles_between"] for p in pairs
        ),
        "largest_windows": sorted(
            pairs, key=lambda p: -p["compute_cycles_between"]
        )[:8],
    }


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--analyze-only":
        hlo = open(sys.argv[2]).read()
    else:
        compiled = build()
        hlo = compiled.as_text()
        with open("/tmp/aot_join_hlo.txt", "w") as f:
            f.write(hlo)
    out = analyze(hlo)
    out["odf"] = ODF
    out["rows_per_dev"] = ROWS_PER_DEV
    out["intra"] = INTRA
    print(json.dumps(out))


if __name__ == "__main__":
    main()
