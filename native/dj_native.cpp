// dj_native: host-side native runtime for dj_tpu.
//
// TPU-native counterpart of the reference's C++/CUDA host runtime
// pieces that remain host work on TPU systems: dataset generation with
// exact selectivity semantics (/root/reference/generate_dataset/
// generate_dataset.cuh:47-259), the MurmurHash3_x86_32 row hash used as
// a host oracle for the device hash (cuDF hashing semantics), and a
// pipe-delimited .tbl column parser (the data-loading role cuDF's
// parquet/CSV readers play in the reference's drivers).
//
// Design notes:
// - Unique build keys and their complement are produced by a Feistel
//   cipher acting as a lazy pseudorandom permutation of [0, rand_max):
//   position i < n_build is a build key, position >= n_build is
//   complement — O(1) memory where the reference uses a device lottery
//   array + atomicCAS and thrust::set_difference.
// - All entry points are plain C ABI for ctypes; buffers are caller
//   allocated (numpy). Work is split across a std::thread pool sized by
//   hardware concurrency (DJ_NATIVE_THREADS overrides).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// Thread pool helper
// ---------------------------------------------------------------------------

static int num_threads() {
  const char* env = std::getenv("DJ_NATIVE_THREADS");
  if (env != nullptr) {
    int v = std::atoi(env);
    if (v > 0) return v;
  }
  unsigned hc = std::thread::hardware_concurrency();
  return hc == 0 ? 1 : static_cast<int>(hc);
}

template <typename F>
static void parallel_for(int64_t n, F f) {
  int nt = num_threads();
  if (nt <= 1 || n < (1 << 16)) {
    f(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; t++) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    ts.emplace_back([=] { f(lo, hi); });
  }
  for (auto& th : ts) th.join();
}

// ---------------------------------------------------------------------------
// MurmurHash3_x86_32 (element hash, cuDF semantics; mirrors
// dj_tpu/ops/hashing.py exactly)
// ---------------------------------------------------------------------------

extern "C" {

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_block(uint32_t h, uint32_t k) {
  k *= 0xCC9E2D51u;
  k = rotl32(k, 15);
  k *= 0x1B873593u;
  h ^= k;
  h = rotl32(h, 13);
  return h * 5u + 0xE6546B64u;
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

static inline uint32_t murmur3_u64(uint64_t bits, uint32_t seed) {
  uint32_t h = seed;
  h = mix_block(h, static_cast<uint32_t>(bits & 0xFFFFFFFFull));
  h = mix_block(h, static_cast<uint32_t>(bits >> 32));
  h ^= 8u;
  return fmix32(h);
}

static inline uint32_t murmur3_u32(uint32_t bits, uint32_t seed) {
  uint32_t h = seed;
  h = mix_block(h, bits);
  h ^= 4u;
  return fmix32(h);
}

// Hash n elements of width 4 or 8 bytes into out[n].
void dj_murmur3_32(const void* data, int64_t n, int width, uint32_t seed,
                   uint32_t* out) {
  if (width == 8) {
    const uint64_t* p = static_cast<const uint64_t*>(data);
    parallel_for(n, [=](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) out[i] = murmur3_u64(p[i], seed);
    });
  } else if (width == 4) {
    const uint32_t* p = static_cast<const uint32_t*>(data);
    parallel_for(n, [=](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) out[i] = murmur3_u32(p[i], seed);
    });
  }
}

// ---------------------------------------------------------------------------
// Feistel permutation over [0, domain) + dataset generator
// ---------------------------------------------------------------------------

// splitmix64: statistically solid 64-bit mixer for round keys / draws.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct Feistel {
  // Balanced Feistel network over 2*half_bits bits with cycle walking
  // to restrict to [0, domain).
  uint64_t domain;
  int half_bits;
  uint64_t half_mask;
  uint64_t keys[4];

  Feistel(uint64_t domain_, uint64_t seed) : domain(domain_) {
    int bits = 1;
    while ((1ull << bits) < domain) bits++;
    half_bits = (bits + 1) / 2;
    half_mask = (1ull << half_bits) - 1;
    for (int r = 0; r < 4; r++) keys[r] = splitmix64(seed + 0x1234 + r);
  }

  inline uint64_t encrypt_once(uint64_t x) const {
    uint64_t l = x >> half_bits;
    uint64_t r = x & half_mask;
    for (int i = 0; i < 4; i++) {
      uint64_t nl = r;
      r = (l ^ splitmix64(r * 0x9E3779B97F4A7C15ull + keys[i])) & half_mask;
      l = nl;
    }
    return (l << half_bits) | r;
  }

  // Permutation of [0, domain): walk cycles until we land inside.
  inline uint64_t operator()(uint64_t x) const {
    uint64_t y = encrypt_once(x);
    while (y >= domain) y = encrypt_once(y);
    return y;
  }
};

// Build/probe generation with the reference's semantics
// (generate_dataset.cuh:137-162): build keys are a uniform draw from
// [0, rand_max] — unique when requested — probe keys hit the build set
// with probability `selectivity`, otherwise draw from its complement.
void dj_generate_build_probe(int64_t n_build, int64_t n_probe,
                             double selectivity, int64_t rand_max,
                             int unique_build, uint64_t seed,
                             int64_t* build_keys, int64_t* probe_keys) {
  uint64_t domain = static_cast<uint64_t>(rand_max) + 1;
  Feistel perm(domain, seed);
  if (unique_build) {
    parallel_for(n_build, [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) {
        build_keys[i] = static_cast<int64_t>(perm(i));
      }
    });
  } else {
    parallel_for(n_build, [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) {
        uint64_t r = splitmix64(seed ^ (0xB0B0ull + i));
        build_keys[i] = static_cast<int64_t>(r % domain);
      }
    });
  }
  uint64_t comp_size = domain > static_cast<uint64_t>(n_build)
                           ? domain - n_build
                           : 1;
  parallel_for(n_probe, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      uint64_t r1 = splitmix64(seed ^ (0xABCDull + i * 3));
      double u = (r1 >> 11) * (1.0 / 9007199254740992.0);  // [0,1)
      uint64_t r2 = splitmix64(seed ^ (0xEF01ull + i * 7));
      if (u < selectivity) {
        probe_keys[i] = build_keys[r2 % static_cast<uint64_t>(n_build)];
      } else if (unique_build) {
        // Complement = permutation positions >= n_build.
        probe_keys[i] =
            static_cast<int64_t>(perm(n_build + (r2 % comp_size)));
      } else {
        // Non-unique build: draw outside [0, rand_max] entirely (the
        // reference derives the complement by set_difference; any value
        // > rand_max is provably a miss and cheaper).
        probe_keys[i] = static_cast<int64_t>(domain + (r2 % domain));
      }
    }
  });
}

// Exact expected inner-join match count for the unique-build generator
// above, by replaying the probe draws: a probe row matches exactly once
// iff its selectivity draw hits (hits are drawn FROM the unique build
// set; misses are complement permutation positions >= n_build, provably
// absent). O(n_probe), no key materialization — the analytical oracle
// the reference gets from a single-GPU reference join
// (/root/reference/test/compare_against_single_gpu.cu:166-207).
int64_t dj_expected_match_count(int64_t n_probe, double selectivity,
                                uint64_t seed) {
  std::atomic<int64_t> total{0};
  parallel_for(n_probe, [&](int64_t lo, int64_t hi) {
    int64_t local = 0;
    for (int64_t i = lo; i < hi; i++) {
      uint64_t r1 = splitmix64(seed ^ (0xABCDull + i * 3));
      double u = (r1 >> 11) * (1.0 / 9007199254740992.0);  // [0,1)
      if (u < selectivity) local++;
    }
    total += local;
  });
  return total.load();
}

// ---------------------------------------------------------------------------
// Pipe-delimited .tbl parser (tpch-dbgen output)
// ---------------------------------------------------------------------------

int64_t dj_tbl_count_rows(const char* data, int64_t len) {
  std::atomic<int64_t> rows{0};
  parallel_for(len, [&](int64_t lo, int64_t hi) {
    int64_t local = 0;
    for (int64_t i = lo; i < hi; i++) {
      if (data[i] == '\n') local++;
    }
    rows += local;
  });
  int64_t r = rows.load();
  if (len > 0 && data[len - 1] != '\n') r++;  // unterminated last row
  return r;
}

// Find start offset of each row (newline + 1); out_starts must hold
// nrows entries. Returns number of rows written.
static int64_t row_starts(const char* data, int64_t len,
                          std::vector<int64_t>& starts) {
  starts.push_back(0);
  for (int64_t i = 0; i < len - 1; i++) {
    if (data[i] == '\n') starts.push_back(i + 1);
  }
  return static_cast<int64_t>(starts.size());
}

// Parse field `field_idx` (0-based, pipe-delimited) of each row as
// int64 into out[nrows]. Returns rows parsed, or -1 on malformed input.
int64_t dj_parse_tbl_int64(const char* data, int64_t len, int32_t field_idx,
                           int64_t* out, int64_t max_rows) {
  std::vector<int64_t> starts;
  int64_t nrows = row_starts(data, len, starts);
  if (nrows > max_rows) return -1;
  std::atomic<bool> ok{true};
  parallel_for(nrows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      const char* p = data + starts[r];
      const char* end = data + (r + 1 < nrows ? starts[r + 1] : len);
      for (int32_t f = 0; f < field_idx && p < end; ) {
        if (*p++ == '|') f++;
      }
      bool neg = false;
      if (p < end && *p == '-') { neg = true; p++; }
      int64_t v = 0;
      bool any = false;
      while (p < end && *p >= '0' && *p <= '9') {
        v = v * 10 + (*p++ - '0');
        any = true;
      }
      if (!any) { ok = false; return; }
      out[r] = neg ? -v : v;
    }
  });
  return ok.load() ? nrows : -1;
}

// Parse field as float64 (decimal, no exponent — dbgen's format).
int64_t dj_parse_tbl_float64(const char* data, int64_t len,
                             int32_t field_idx, double* out,
                             int64_t max_rows) {
  std::vector<int64_t> starts;
  int64_t nrows = row_starts(data, len, starts);
  if (nrows > max_rows) return -1;
  parallel_for(nrows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      const char* p = data + starts[r];
      const char* end = data + (r + 1 < nrows ? starts[r + 1] : len);
      for (int32_t f = 0; f < field_idx && p < end; ) {
        if (*p++ == '|') f++;
      }
      bool neg = false;
      if (p < end && *p == '-') { neg = true; p++; }
      double v = 0;
      while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
      if (p < end && *p == '.') {
        p++;
        double scale = 0.1;
        while (p < end && *p >= '0' && *p <= '9') {
          v += (*p++ - '0') * scale;
          scale *= 0.1;
        }
      }
      out[r] = neg ? -v : v;
    }
  });
  return nrows;
}

// String field: pass 1 writes per-row byte sizes; pass 2 (chars !=
// nullptr) fills the packed char buffer at the provided offsets.
int64_t dj_parse_tbl_string(const char* data, int64_t len,
                            int32_t field_idx, int32_t* sizes,
                            const int32_t* offsets, uint8_t* chars,
                            int64_t max_rows) {
  std::vector<int64_t> starts;
  int64_t nrows = row_starts(data, len, starts);
  if (nrows > max_rows) return -1;
  parallel_for(nrows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      const char* p = data + starts[r];
      const char* end = data + (r + 1 < nrows ? starts[r + 1] : len);
      for (int32_t f = 0; f < field_idx && p < end; ) {
        if (*p++ == '|') f++;
      }
      const char* q = p;
      while (q < end && *q != '|' && *q != '\n') q++;
      if (chars == nullptr) {
        sizes[r] = static_cast<int32_t>(q - p);
      } else {
        std::memcpy(chars + offsets[r], p, q - p);
      }
    }
  });
  return nrows;
}

}  // extern "C"
